"""Finite-difference gradient checks (SURVEY §4 OpTest pattern) for the
round-4 surface-sweep ops (VERDICT r4 #5: the sweep added dozens of
differentiable ops with forward-only tests; check_grad is the OpTest
default and is applied retroactively here).

Index/mask arguments are closed over (not differentiable); every float
input is finite-differenced.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import OpTest

F = paddle.nn.functional


class TestTakeScatterFamilyGrads(OpTest):
    def test_take_grad(self):
        rs = np.random.RandomState(0)
        x = rs.randn(3, 4)
        idx = paddle.to_tensor(np.array([0, 5, 11, 3]))
        self.check_grad(lambda xt: paddle.take(xt, idx), [x])

    def test_take_along_axis_grad(self):
        rs = np.random.RandomState(1)
        x = rs.randn(3, 4)
        idx = paddle.to_tensor(np.array([[0, 2, 1, 3]], dtype=np.int64))
        self.check_grad(
            lambda xt: paddle.take_along_axis(xt, idx, axis=1), [x])

    def test_put_along_axis_assign_grad(self):
        rs = np.random.RandomState(2)
        x = rs.randn(3, 4)
        v = rs.randn(1, 4)
        idx = paddle.to_tensor(np.array([[0, 2, 1, 0]], dtype=np.int64))
        self.check_grad(
            lambda xt, vt: paddle.put_along_axis(xt, idx, vt, axis=0),
            [x, v])

    def test_put_along_axis_add_grad(self):
        rs = np.random.RandomState(3)
        x = rs.randn(3, 4)
        v = rs.randn(1, 4)
        idx = paddle.to_tensor(np.array([[1, 1, 2, 0]], dtype=np.int64))
        self.check_grad(
            lambda xt, vt: paddle.put_along_axis(xt, idx, vt, axis=0,
                                                 reduce="add"), [x, v])

    def test_scatter_grad(self):
        rs = np.random.RandomState(4)
        x = rs.randn(4, 3)
        u = rs.randn(2, 3)
        idx = paddle.to_tensor(np.array([1, 3]))
        self.check_grad(
            lambda xt, ut: paddle.scatter(xt, idx, ut, overwrite=True),
            [x, u])

    def test_scatter_nd_grad(self):
        rs = np.random.RandomState(5)
        u = rs.randn(2, 3)
        idx = paddle.to_tensor(np.array([[1], [3]], dtype=np.int64))
        self.check_grad(
            lambda ut: paddle.scatter_nd(idx, ut, [5, 3]), [u])

    def test_scatter_nd_add_grad(self):
        rs = np.random.RandomState(6)
        x = rs.randn(5, 3)
        u = rs.randn(2, 3)
        idx = paddle.to_tensor(np.array([[1], [1]], dtype=np.int64))
        self.check_grad(
            lambda xt, ut: paddle.scatter_nd_add(xt, idx, ut), [x, u])

    def test_index_add_grad(self):
        rs = np.random.RandomState(7)
        x = rs.randn(4, 3)
        v = rs.randn(2, 3)
        idx = paddle.to_tensor(np.array([0, 2]))
        self.check_grad(
            lambda xt, vt: paddle.index_add(xt, idx, 0, vt), [x, v])

    def test_index_put_grad(self):
        rs = np.random.RandomState(8)
        x = rs.randn(4, 3)
        v = rs.randn(2)
        i0 = paddle.to_tensor(np.array([1, 3]))
        i1 = paddle.to_tensor(np.array([0, 2]))
        self.check_grad(
            lambda xt, vt: paddle.index_put(xt, (i0, i1), vt), [x, v])

    def test_index_fill_grad(self):
        rs = np.random.RandomState(9)
        x = rs.randn(4, 3)
        idx = paddle.to_tensor(np.array([0, 2]))
        self.check_grad(
            lambda xt: paddle.index_fill(xt, idx, 0, 1.5), [x])

    def test_masked_fill_grad(self):
        rs = np.random.RandomState(10)
        x = rs.randn(3, 4)
        mask = paddle.to_tensor(rs.rand(3, 4) > 0.5)
        self.check_grad(
            lambda xt: paddle.masked_fill(xt, mask, 2.0), [x])

    def test_masked_scatter_grad(self):
        rs = np.random.RandomState(11)
        x = rs.randn(3, 4)
        v = rs.randn(12)
        mask = paddle.to_tensor(rs.rand(3, 4) > 0.5)
        self.check_grad(
            lambda xt, vt: paddle.masked_scatter(xt, mask, vt), [x, v])

    def test_select_scatter_grad(self):
        rs = np.random.RandomState(12)
        x = rs.randn(3, 4)
        v = rs.randn(4)
        self.check_grad(
            lambda xt, vt: paddle.select_scatter(xt, vt, axis=0, index=1),
            [x, v])

    def test_slice_scatter_grad(self):
        rs = np.random.RandomState(13)
        x = rs.randn(4, 5)
        v = rs.randn(4, 2)
        self.check_grad(
            lambda xt, vt: paddle.slice_scatter(
                xt, vt, axes=[1], starts=[1], ends=[3], strides=[1]),
            [x, v])

    def test_diagonal_scatter_grad(self):
        rs = np.random.RandomState(14)
        x = rs.randn(4, 4)
        v = rs.randn(4)
        self.check_grad(
            lambda xt, vt: paddle.diagonal_scatter(xt, vt), [x, v])


class TestSplitFamilyGrads(OpTest):
    def test_split_grad(self):
        rs = np.random.RandomState(20)
        x = rs.randn(6, 4)

        def op(xt):
            a, b, c = paddle.split(xt, 3, axis=0)
            return a * 1.0 + b * 2.0 + c * 3.0
        self.check_grad(op, [x])

    def test_tensor_split_grad(self):
        rs = np.random.RandomState(21)
        x = rs.randn(7, 3)

        def op(xt):
            parts = paddle.tensor_split(xt, 3, axis=0)
            return sum(paddle.sum(p) * (i + 1)
                       for i, p in enumerate(parts))
        self.check_grad(op, [x])

    def test_hsplit_vsplit_dsplit_grad(self):
        rs = np.random.RandomState(22)
        x = rs.randn(4, 4, 4)

        def op(xt):
            h = paddle.hsplit(xt, 2)[0]
            v = paddle.vsplit(xt, 2)[1]
            d = paddle.dsplit(xt, 2)[0]
            return paddle.sum(h) + 2.0 * paddle.sum(v) + 3.0 * paddle.sum(d)
        self.check_grad(op, [x])

    def test_chunk_grad(self):
        rs = np.random.RandomState(23)
        x = rs.randn(6, 2)

        def op(xt):
            a, b = paddle.chunk(xt, 2, axis=0)
            return paddle.sum(a * a) + paddle.sum(b * 3.0)
        self.check_grad(op, [x])

    def test_unstack_grad(self):
        rs = np.random.RandomState(24)
        x = rs.randn(3, 4)

        def op(xt):
            parts = paddle.unstack(xt, axis=0)
            return sum(paddle.sum(p) * (i + 1)
                       for i, p in enumerate(parts))
        self.check_grad(op, [x])


class TestGammaFamilyGrads(OpTest):
    def _pos(self, rs, *shape):
        return rs.rand(*shape) * 2.0 + 0.5

    def test_lgamma_gammaln_grad(self):
        rs = np.random.RandomState(30)
        x = self._pos(rs, 3, 4)
        self.check_grad(paddle.lgamma, [x])
        self.check_grad(paddle.gammaln, [x])

    def test_digamma_grad(self):
        rs = np.random.RandomState(31)
        self.check_grad(paddle.digamma, [self._pos(rs, 3, 4)])

    def test_polygamma_grad(self):
        rs = np.random.RandomState(32)
        self.check_grad(lambda xt: paddle.polygamma(xt, 1),
                        [self._pos(rs, 3, 3)])

    def test_multigammaln_grad(self):
        rs = np.random.RandomState(33)
        x = rs.rand(3, 3) * 2.0 + 3.0     # must exceed (p-1)/2
        self.check_grad(lambda xt: paddle.multigammaln(xt, 2), [x])

    def test_gammainc_grad_wrt_x(self):
        rs = np.random.RandomState(34)
        a = paddle.to_tensor(self._pos(rs, 3, 3))
        x = self._pos(rs, 3, 3)
        self.check_grad(lambda xt: paddle.gammainc(a, xt), [x])

    def test_gammaincc_grad_wrt_x(self):
        rs = np.random.RandomState(35)
        a = paddle.to_tensor(self._pos(rs, 3, 3))
        x = self._pos(rs, 3, 3)
        self.check_grad(lambda xt: paddle.gammaincc(a, xt), [x])

    def test_bessel_i_grad(self):
        rs = np.random.RandomState(36)
        x = rs.randn(3, 4)
        for op in (paddle.i0, paddle.i0e, paddle.i1, paddle.i1e):
            self.check_grad(op, [x])


class TestLinalgGrads(OpTest):
    def test_lu_unpack_grad(self):
        rs = np.random.RandomState(40)
        a = rs.randn(3, 3) + np.eye(3) * 3.0
        lu, piv = paddle.linalg.lu(paddle.to_tensor(a.astype("f4")))
        lu_np = np.asarray(lu._value)

        def op(lut):
            p, l_mat, u = paddle.linalg.lu_unpack(lut, piv)
            return paddle.sum(l_mat * 2.0) + paddle.sum(u * 3.0)
        self.check_grad(op, [lu_np])

    def test_ormqr_grad(self):
        # grads wrt all three float inputs: reflectors, tau, other
        rs = np.random.RandomState(41)
        inp = rs.randn(4, 3) * 0.5
        tau = rs.rand(3) * 0.5
        other = rs.randn(3, 4)
        self.check_grad(
            lambda it, tt, ot: paddle.linalg.ormqr(it, tt, ot),
            [inp, tau, other], rtol=3e-2, atol=3e-3)

    def test_householder_product_complex_parity(self):
        # code-review r5: the reflector application must conjugate
        # (H = I - tau v v^H); golden = the LAPACK-backed jax primitive
        import jax
        import jax.numpy as jnp
        rs = np.random.RandomState(43)
        a = (rs.randn(4, 3) + 1j * rs.randn(4, 3)).astype("complex64") \
            * 0.5
        tau = (rs.rand(3) + 0.3j * rs.rand(3)).astype("complex64")
        ref = jax.lax.linalg.householder_product(jnp.asarray(a),
                                                 jnp.asarray(tau))
        got = paddle.householder_product(paddle.to_tensor(a),
                                         paddle.to_tensor(tau))
        np.testing.assert_allclose(np.asarray(got._value),
                                   np.asarray(ref), rtol=1e-5, atol=1e-6)

    def test_householder_product_grad(self):
        rs = np.random.RandomState(42)
        a = rs.randn(4, 3) * 0.5
        tau = rs.rand(3) * 0.5
        self.check_grad(paddle.householder_product, [a, tau],
                        rtol=3e-2, atol=3e-3)


class TestNNExtrasGrads(OpTest):
    def test_embedding_bag_grads(self):
        rs = np.random.RandomState(50)
        w = rs.randn(10, 4) * 0.5
        ids = paddle.to_tensor(np.array([[1, 2, 3], [4, 5, 6]]))
        for mode in ("mean", "sum"):
            self.check_grad(
                lambda wt, m=mode: F.embedding_bag(ids, wt, mode=m), [w])

    def test_prelu_element_mode_grads(self):
        rs = np.random.RandomState(51)
        x = rs.randn(2, 3, 4)
        alpha = rs.rand(3, 4) * 0.5

        def op(xt, at):
            return F.prelu(xt, at)
        self.check_grad(op, [x, alpha])

    def test_glu_grad(self):
        rs = np.random.RandomState(52)
        self.check_grad(lambda xt: F.glu(xt, axis=-1), [rs.randn(3, 8)])

    def test_fused_mha_with_mask_grad(self):
        # fused op with an attention mask: mask is closed over, all
        # float inputs checked (VERDICT "fused ops with masks" row)
        rs = np.random.RandomState(53)
        B, S, H, Dh = 1, 3, 1, 4
        C = H * Dh
        x = rs.randn(B, S, C) * 0.5
        wq = rs.randn(3, H, Dh, C) * 0.2
        wl = rs.randn(C, C) * 0.2
        mask = np.zeros((B, H, S, S), "f4")
        mask[..., 2] = -1e9               # mask out the last key
        mask_t = paddle.to_tensor(mask)
        Fi = paddle.incubate.nn.functional

        def op(xt, wqt, wlt):
            return Fi.fused_multi_head_attention(
                xt, wqt, wlt, attn_mask=mask_t, dropout_rate=0.0,
                attn_dropout_rate=0.0, training=False)
        self.check_grad(op, [x, wq, wl], rtol=3e-2, atol=3e-3)

    def test_softmax_mask_fuse_grad(self):
        rs = np.random.RandomState(54)
        Fi = paddle.incubate.nn.functional
        if not hasattr(Fi, "softmax_mask_fuse"):
            pytest.skip("softmax_mask_fuse not available")
        x = rs.randn(1, 1, 4, 4)
        mask = paddle.to_tensor(
            (rs.rand(1, 1, 4, 4) > 0.3).astype("f4") * -1e9)
        self.check_grad(lambda xt: Fi.softmax_mask_fuse(xt, mask), [x],
                        rtol=3e-2, atol=3e-3)

    def test_gather_nd_grad(self):
        rs = np.random.RandomState(55)
        x = rs.randn(3, 4)
        idx = paddle.to_tensor(np.array([[0, 1], [2, 3]], dtype=np.int64))
        self.check_grad(lambda xt: paddle.gather_nd(xt, idx), [x])
