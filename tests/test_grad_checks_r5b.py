"""Finite-difference gradient checks, second r5 sweep (SURVEY §4 OpTest
pattern): differentiable ops that until now carried forward-only tests —
pointwise/binary factories (erfinv, logit, atan2, hypot, copysign),
reductions and shaping (logsumexp, trapezoid, diff, kron, outer, lerp,
cross, renorm, cdist, kthvalue), and the linalg ladder (cholesky,
triangular_solve, matrix_power, pinv, det/slogdet, qr, svd, lu).

Domain handling: inputs are kept away from non-differentiable points
(|x|<1 for erfinv, (0,1) for logit, SPD/well-conditioned matrices for
the linalg ops, nonzero rows for norms/distances).
"""
import numpy as np

import paddle_tpu as paddle
from op_test import OpTest


class TestPointwiseGrads(OpTest):
    def test_erfinv_grad(self):
        rs = np.random.RandomState(0)
        x = rs.uniform(-0.9, 0.9, (3, 4))
        self.check_grad(lambda t: paddle.erfinv(t), [x])

    def test_logit_grad(self):
        rs = np.random.RandomState(1)
        x = rs.uniform(0.1, 0.9, (3, 4))
        self.check_grad(lambda t: paddle.logit(t), [x])

    def test_atan2_grad(self):
        rs = np.random.RandomState(2)
        y = rs.uniform(0.5, 2.0, (3, 4)) * np.sign(rs.randn(3, 4))
        x = rs.uniform(0.5, 2.0, (3, 4))
        self.check_grad(lambda a, b: paddle.atan2(a, b), [y, x])

    def test_hypot_grad(self):
        rs = np.random.RandomState(3)
        a = rs.uniform(0.5, 2.0, (3, 4))
        b = rs.uniform(0.5, 2.0, (3, 4))
        self.check_grad(lambda x, y: paddle.hypot(x, y), [a, b])

    def test_copysign_grad_wrt_magnitude(self):
        rs = np.random.RandomState(4)
        x = rs.uniform(0.5, 2.0, (3, 4)) * np.sign(rs.randn(3, 4))
        y = rs.uniform(0.5, 2.0, (3, 4)) * np.sign(rs.randn(3, 4))
        # d/dy is 0 a.e. (sign is piecewise constant) — checked too
        self.check_grad(lambda a, b: paddle.copysign(a, b), [x, y])

    def test_lerp_grad_all_inputs(self):
        rs = np.random.RandomState(5)
        x, y = rs.randn(3, 4), rs.randn(3, 4)
        w = rs.uniform(0.2, 0.8, (3, 4))
        self.check_grad(lambda a, b, c: paddle.lerp(a, b, c), [x, y, w])


class TestReductionShapingGrads(OpTest):
    def test_logsumexp_grad(self):
        rs = np.random.RandomState(6)
        x = rs.randn(3, 5)
        self.check_grad(lambda t: paddle.logsumexp(t, axis=1), [x])

    def test_trapezoid_grad(self):
        rs = np.random.RandomState(7)
        y = rs.randn(4, 6)
        self.check_grad(lambda t: paddle.trapezoid(t, dx=0.5, axis=1),
                        [y])

    def test_diff_grad(self):
        rs = np.random.RandomState(8)
        x = rs.randn(3, 6)
        self.check_grad(lambda t: paddle.diff(t, axis=1), [x])

    def test_kron_grad(self):
        rs = np.random.RandomState(9)
        a, b = rs.randn(2, 3), rs.randn(3, 2)
        self.check_grad(lambda x, y: paddle.kron(x, y), [a, b])

    def test_outer_grad(self):
        rs = np.random.RandomState(10)
        a, b = rs.randn(4), rs.randn(5)
        self.check_grad(lambda x, y: paddle.outer(x, y), [a, b])

    def test_cross_grad(self):
        rs = np.random.RandomState(11)
        a, b = rs.randn(4, 3), rs.randn(4, 3)
        self.check_grad(lambda x, y: paddle.cross(x, y, axis=1), [a, b])

    def test_renorm_grad(self):
        rs = np.random.RandomState(12)
        # every row norm well above maxnorm: smooth scaling regime
        x = rs.randn(4, 6) * 5.0 + np.sign(rs.randn(4, 6)) * 2.0
        self.check_grad(
            lambda t: paddle.renorm(t, p=2.0, axis=0, max_norm=1.0), [x])

    def test_cdist_grad(self):
        rs = np.random.RandomState(13)
        a, b = rs.randn(4, 3), rs.randn(5, 3) + 3.0  # no zero distances
        self.check_grad(lambda x, y: paddle.cdist(x, y), [a, b])

    def test_kthvalue_grad(self):
        rs = np.random.RandomState(14)
        x = rs.randn(3, 7)
        self.check_grad(lambda t: paddle.kthvalue(t, k=3, axis=1)[0], [x])


def _well_conditioned(rs, n):
    return rs.randn(n, n) + n * np.eye(n)


class TestLinalgGrads(OpTest):
    def test_cholesky_grad(self):
        rs = np.random.RandomState(15)
        m = rs.randn(3, 3)

        def fn(t):
            spd = t @ t.t() + paddle.eye(3) * 3.0
            return paddle.linalg.cholesky(spd)
        self.check_grad(fn, [m])

    def test_triangular_solve_grad(self):
        rs = np.random.RandomState(16)
        lo = np.tril(rs.randn(3, 3)) + 3.0 * np.eye(3)
        b = rs.randn(3, 2)
        self.check_grad(
            lambda a, y: paddle.linalg.triangular_solve(a, y, upper=False),
            [lo, b])

    def test_matrix_power_grad(self):
        rs = np.random.RandomState(17)
        m = _well_conditioned(rs, 3)
        self.check_grad(lambda t: paddle.linalg.matrix_power(t, 3), [m])

    def test_matrix_power_negative_grad(self):
        rs = np.random.RandomState(18)
        m = _well_conditioned(rs, 3)
        self.check_grad(lambda t: paddle.linalg.matrix_power(t, -1), [m])

    def test_pinv_grad(self):
        rs = np.random.RandomState(19)
        m = rs.randn(4, 3)  # full column rank a.s.
        self.check_grad(lambda t: paddle.linalg.pinv(t), [m],
                        rtol=2e-2, atol=2e-3)

    def test_det_and_slogdet_grad(self):
        rs = np.random.RandomState(20)
        m = _well_conditioned(rs, 3)
        self.check_grad(lambda t: paddle.linalg.det(t), [m])
        self.check_grad(lambda t: paddle.linalg.slogdet(t)[1], [m])

    def test_qr_grad(self):
        rs = np.random.RandomState(21)
        m = _well_conditioned(rs, 3)
        self.check_grad(lambda t: paddle.linalg.qr(t)[1], [m],
                        rtol=2e-2, atol=2e-3)

    def test_svd_singular_values_grad(self):
        rs = np.random.RandomState(22)
        m = rs.randn(4, 3)
        self.check_grad(
            lambda t: paddle.linalg.svd(t, full_matrices=False)[1], [m])

    def test_lu_grad(self):
        rs = np.random.RandomState(23)
        m = _well_conditioned(rs, 3)
        self.check_grad(lambda t: paddle.linalg.lu(t)[0], [m],
                        rtol=2e-2, atol=2e-3)
