"""Communication-efficient gradient reduction (distributed/grad_comm.py).

Mesh parity on the 8-virtual-device CPU conftest mesh (the reference's
multi-process golden-model pattern): the bucketed/overlapped — and
quantized, at its documented tolerance — DP stepper must match the
single-device stepper, and bucketing alone must not change the update
at all (bitwise).  Accuracy contract: docs/DISTRIBUTED.md.
"""
import textwrap
import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.base.distributed_strategy import \
    DistributedStrategy
from paddle_tpu.distributed.grad_comm import (GradCommConfig, plan_buckets,
                                              build_grad_reducer)

pytestmark = pytest.mark.multichip


def _strategy(**cfgs):
    st = DistributedStrategy()
    st.grad_comm = cfgs.pop("enabled", True)
    st.grad_comm_configs = cfgs
    return st


def _make_model(seed=0):
    paddle.seed(seed)
    return nn.Sequential(
        nn.Linear(16, 64), nn.ReLU(),
        nn.Linear(64, 64), nn.ReLU(),
        nn.Linear(64, 10),
    )


def _train(net, steps=4, bs=16):
    model = paddle.Model(net)
    inner = net._layers if hasattr(net, "_layers") else net
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=inner.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        x = rng.rand(bs, 16).astype("f4")
        y = rng.randint(0, 10, (bs, 1)).astype("i8")
        losses.append(model.train_batch([x], [y])[0])
    return losses, inner


# -- bucket planning (pure host code) ---------------------------------------

class TestBucketPlan:
    SHAPES = [(100,), (200, 4), (50,), (3000,), (10,)]
    DTYPES = [jnp.float32] * 5

    def test_reverse_order_partition_covers_all_params_once(self):
        plan = plan_buckets(self.SHAPES, self.DTYPES, 1600)
        flat = [i for b in plan.buckets for i in b]
        assert sorted(flat) == list(range(len(self.SHAPES)))
        # reverse parameter order: backward produces the LAST params'
        # grads first, so the first bucket must hold the highest indices
        assert flat == list(reversed(range(len(self.SHAPES))))

    def test_bucket_sizes_and_oversized_tensor(self):
        plan = plan_buckets(self.SHAPES, self.DTYPES, 1600)
        # per-bucket byte counts match their members
        for idxs, nb in zip(plan.buckets, plan.nbytes):
            assert nb == sum(int(np.prod(self.SHAPES[i])) * 4
                             for i in idxs)
        assert plan.total_bytes == sum(
            int(np.prod(s)) * 4 for s in self.SHAPES)
        # the 3000-element tensor (12000 B > 1600 B target) closes a
        # bucket on its own rather than splitting across reduces
        assert any(nb >= 12000 for nb in plan.nbytes)
        # every bucket except possibly the last reached the target
        assert all(nb >= 1600 for nb in plan.nbytes[:-1])

    def test_overlap_fraction_structural(self):
        one = plan_buckets([(8,)], [jnp.float32], 1 << 30)
        assert one.overlap_fraction == 0.0
        multi = plan_buckets(self.SHAPES, self.DTYPES, 1600)
        assert len(multi.buckets) > 1
        expect = 1.0 - multi.nbytes[-1] / multi.total_bytes
        assert multi.overlap_fraction == pytest.approx(expect)
        assert 0.0 < multi.overlap_fraction < 1.0


class TestGradCommConfig:
    def test_from_strategy_off_is_none(self):
        assert GradCommConfig.from_strategy(None) is None
        assert GradCommConfig.from_strategy(DistributedStrategy()) is None

    def test_bucket_mb_defaults_to_fuse_knob(self):
        st = _strategy()
        st.fuse_grad_size_in_MB = 7
        cc = GradCommConfig.from_strategy(st)
        assert cc.enabled and cc.bucket_mb == 7.0

    def test_zero1_and_reducer_mutually_exclusive(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            GradCommConfig(enabled=True, zero1=True)

    def test_unknown_quantize_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown quantize mode"):
            GradCommConfig(quantize="int4")

    def test_fp8_falls_back_when_unavailable(self):
        cc = GradCommConfig(quantize="fp8")
        if getattr(jnp, "float8_e4m3fn", None) is None:
            assert cc.quantize == "int8" and cc.fp8_fallback
        else:
            assert cc.quantize == "fp8" and not cc.fp8_fallback


# -- reducer on the 8-device mesh -------------------------------------------

class TestReducerOnMesh:
    def test_bucket_gauges_recorded(self):
        from paddle_tpu import observability as obs
        obs.get_registry().reset()
        shapes, dtypes = [(64, 8), (128,), (32, 32)], [jnp.float32] * 3
        _, plan = build_grad_reducer(shapes, dtypes,
                                     GradCommConfig(bucket_mb=0.001),
                                     "data", 8)
        reg = obs.get_registry()
        assert reg.get("pt_collective_grad_buckets").value() == \
            len(plan.buckets)
        assert reg.get("pt_collective_overlap_fraction").value() == \
            pytest.approx(plan.overlap_fraction)

    def test_quant_reduce_tracks_exact_mean(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        assert jax.device_count() == 8
        mesh = Mesh(np.asarray(jax.devices()), ("data",))
        shapes = [(33, 7), (129,), (64, 3)]
        dtypes = [jnp.float32] * 3
        cfg = GradCommConfig(bucket_mb=0.0005, quantize="int8",
                             quant_chunk=50)
        reducer, plan = build_grad_reducer(shapes, dtypes, cfg, "data", 8)
        assert len(plan.buckets) >= 2

        def body():
            r = jax.lax.axis_index("data")
            grads = [jax.random.normal(
                jax.random.fold_in(jax.random.key(3), r * 16 + i), s)
                for i, s in enumerate(shapes)]
            exact = [jax.lax.pmean(g, "data") for g in grads]
            approx = reducer(list(grads))  # the reducer's DP mean
            return tuple(exact) + tuple(approx)

        out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                                out_specs=tuple(P() for _ in range(6)),
                                check_rep=False))()
        exact, approx = out[:3], out[3:]
        for e, a in zip(exact, approx):
            amax = float(jnp.max(jnp.abs(e)))
            # two absmax-scaled int8 phases: per-element error is
            # bounded by ~2/127 of the group amax (docs/DISTRIBUTED.md)
            assert float(jnp.max(jnp.abs(e - a))) <= 0.05 * max(amax, 1e-6)


# -- DP stepper parity (the satellite contract) -----------------------------

class TestDPStepperParity:
    def test_fp32_bucketed_overlapped_matches_single_device(self):
        assert jax.device_count() == 8
        golden, _ = _train(_make_model(seed=7))
        net = _make_model(seed=7)
        dp = paddle.DataParallel(net, strategy=_strategy(bucket_mb=0.001))
        assert dp._placement_plan.grad_comm is not None
        losses, inner = _train(dp)
        # fp32 wire: same math as the GSPMD all-reduce, tight tolerance
        np.testing.assert_allclose(losses, golden, rtol=1e-5, atol=1e-5)
        assert inner.parameters()[0]._value.sharding.is_fully_replicated

    @pytest.mark.parametrize("mode", ["bf16", "int8"])
    def test_quantized_wire_tracks_fp32_at_documented_tolerance(self, mode):
        golden, _ = _train(_make_model(seed=7))
        net = _make_model(seed=7)
        dp = paddle.DataParallel(
            net, strategy=_strategy(bucket_mb=0.001, quantize=mode))
        losses, _ = _train(dp)
        # documented accuracy contract (docs/DISTRIBUTED.md): quantized
        # wire formats track the fp32 loss, they do not equal it
        np.testing.assert_allclose(losses, golden, rtol=0, atol=3e-2)

    def test_bucketing_alone_is_bitwise_invariant(self):
        """Bucket partitioning (many small buckets vs one monolithic
        reduce) must not change the update AT ALL — same psum values,
        same order, bitwise-equal parameters."""
        net_a = _make_model(seed=5)
        dp_a = paddle.DataParallel(net_a,
                                   strategy=_strategy(bucket_mb=0.001))
        _train(dp_a)
        net_b = _make_model(seed=5)
        dp_b = paddle.DataParallel(net_b,
                                   strategy=_strategy(overlap=False))
        _train(dp_b)
        pa = [np.asarray(p._value) for p in net_a.parameters()]
        pb = [np.asarray(p._value) for p in net_b.parameters()]
        for a, b in zip(pa, pb):
            np.testing.assert_array_equal(a, b)

    def test_indivisible_batch_raises_before_compile(self):
        net = _make_model(seed=1)
        dp = paddle.DataParallel(net, strategy=_strategy())
        with pytest.raises(ValueError, match="not divisible"):
            _train(dp, steps=1, bs=12)   # 12 % 8 != 0

    def test_nondp_plan_warns_and_falls_back(self):
        from jax.sharding import Mesh
        from paddle_tpu.distributed.engine import PlacementPlan
        devs = np.asarray(jax.devices()).reshape(4, 2)
        plan = PlacementPlan(Mesh(devs, ("data", "sharding")), level="os",
                             grad_comm=GradCommConfig())
        net = _make_model(seed=2)
        net._placement_plan = plan
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            losses, _ = _train(net, steps=1)
        assert any("grad_comm" in str(w.message) for w in caught)
        assert np.isfinite(losses[0])


class TestZero1Flag:
    def test_zero1_routes_to_os_plan_and_matches_golden(self):
        golden, _ = _train(_make_model(seed=3))
        st = DistributedStrategy()
        st.grad_comm_configs = {"zero1": True}  # flag alone, reducer off
        net = _make_model(seed=3)
        dp = paddle.DataParallel(net, strategy=st)
        plan = dp._placement_plan
        assert plan.level == "os"
        assert plan.grad_comm is None  # ZeRO-1 is plan-based, no reducer
        model = paddle.Model(dp)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(4):
            x = rng.rand(16, 16).astype("f4")
            y = rng.randint(0, 10, (16, 1)).astype("i8")
            losses.append(model.train_batch([x], [y])[0])
        np.testing.assert_allclose(losses, golden, rtol=2e-4, atol=2e-5)
        sharded_any = any(
            hasattr(v, "sharding") and v.ndim >= 1 and
            not v.sharding.is_fully_replicated
            for st_ in model._stepper.opt_state for v in st_.values())
        assert sharded_any, "zero1: optimizer state stayed replicated"


# -- static-analysis integration --------------------------------------------

class TestAnalysisIntegration:
    def test_reducer_surfaces_registered(self):
        from paddle_tpu.analysis import registered_surfaces
        quals = {q for _, q in registered_surfaces()}
        assert "build_grad_reducer.reduce" in quals
        assert "_build_quant_reduce.quant_reduce" in quals

    def test_collective_order_walks_reducer_wrappers(self, tmp_path):
        """A rank-conditional call to a grad_comm wrapper is exactly as
        deadlock-prone as one to the raw collective it wraps — the
        extended COLLECTIVE_CALLEES must make the pass flag it."""
        from paddle_tpu.analysis.runner import run_passes
        (tmp_path / "fixture.py").write_text(textwrap.dedent("""
            def step(rank, vec, reduce_vec, reducer):
                if rank == 0:
                    reduce_vec(vec)
                out = reducer([vec])
                return out
            """))
        found = run_passes(paths=[str(tmp_path)],
                           passes=["collective-order"])
        assert [f.code for f in found] == ["rank-conditional-collective"]
        assert "reduce_vec" in found[0].message
