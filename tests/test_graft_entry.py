"""The driver's dryrun_multichip contract must hold WITHOUT the test
harness: __graft_entry__ has to obtain its own virtual CPU mesh even when
the calling process already initialized a different jax backend (round-1
failure mode: the axon sitecustomize claimed the TPU and the dryrun
crashed with a libtpu version mismatch — MULTICHIP_r01.json RED)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_in_process():
    # conftest already forced an 8-device CPU mesh in this process; the
    # entry must detect that and run inline without spawning anything.
    import __graft_entry__ as g

    assert g._ensure_cpu_devices(8)
    g.dryrun_multichip(8)


def test_dryrun_multichip_reexecs_when_backend_claimed():
    # Fresh interpreter that pre-initializes a 1-device backend before
    # calling the entry: dryrun must notice the mesh is unusable and
    # re-exec itself in a clean subprocess rather than crash.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # 1 CPU device only
    env.pop("_GRAFT_DRYRUN_SUBPROCESS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import jax, jax.numpy as jnp\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jnp.zeros(()).block_until_ready()  # initialize 1-device backend\n"
        "assert len(jax.devices()) < 8\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n"
        "print('REEXEC-PATH-OK')\n"
    )
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-4000:]
    assert "REEXEC-PATH-OK" in r.stdout
    assert "fleet dp=" in r.stdout  # the dryrun body itself really ran
