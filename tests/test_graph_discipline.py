"""Graph-discipline lints (ISSUE 11): donation/aliasing, retrace-hazard
and host-concurrency passes.

Sibling of tests/test_static_analysis.py, same contract: every pass is
exercised with seeded-violation fixtures it MUST flag and known-good
idioms it must NOT, plus pragma/baseline interplay, the runner's
--passes listing, the --changed-only scoping, a self-lint proving the
real tree is clean, and the surface-label cross-reference against the
compilestats vocabulary (static and runtime retrace findings share one
language).
"""
import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.analysis import runner as runner_mod
from paddle_tpu.analysis import allowlist
from paddle_tpu.analysis.runner import (run_passes, make_context,
                                        write_baseline, load_baseline,
                                        split_new, REPO_ROOT)

pytestmark = pytest.mark.lint

NEW_PASSES = ["donation", "retrace-hazard", "concurrency"]


def _lint(tmp_path, code, passes, name="fixture.py"):
    (tmp_path / name).write_text(textwrap.dedent(code))
    return run_passes(paths=[str(tmp_path)], passes=passes)


def _codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

class TestDonationPass:
    def test_missing_donation_on_state_tree_surface(self, tmp_path):
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(params, opt_state, lr):
                return params, opt_state

            f = jax.jit(step)
            """, ["donation"])
        assert _codes(found) == ["missing-donation"]
        assert "donate_argnums" in found[0].message

    def test_donated_surface_is_quiet(self, tmp_path):
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(params, opt_state, lr):
                return params, opt_state

            f = jax.jit(step, donate_argnums=(0, 1))
            """, ["donation"])
        assert found == []

    def test_no_state_tree_params_is_quiet(self, tmp_path):
        # a surface over scalars/activations has nothing worth donating
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def kernel(x, scale):
                return x * scale

            f = jax.jit(kernel)
            """, ["donation"])
        assert found == []

    def test_builder_pattern_checked_inside_surface(self, tmp_path):
        # hapi style: @jit_surface on the BUILDER, jit on the nested def
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.analysis import jit_surface

            class Stepper:
                @jit_surface
                def _build(self):
                    def step(train_vals, opt_state, lr):
                        return train_vals, opt_state
                    return jax.jit(step)
            """, ["donation"])
        assert _codes(found) == ["missing-donation"]

    def test_use_after_donate(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            def step(params, batch):
                return params

            def train(params, batch):
                g = jax.jit(step, donate_argnums=(0,))
                new_params = g(params, batch)
                return params[0] + new_params[0]
            """, ["donation"])
        assert _codes(found) == ["use-after-donate"]

    def test_rebound_name_after_donate_is_quiet(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            def step(params, batch):
                return params

            def train(params, batch):
                g = jax.jit(step, donate_argnums=(0,))
                params = g(params, batch)
                return params[0]
            """, ["donation"])
        assert found == []

    def test_double_donation_one_call(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            def step(k_pool, v_pool):
                return k_pool, v_pool

            def serve(pool):
                g = jax.jit(step, donate_argnums=(0, 1))
                return g(pool, pool)
            """, ["donation"])
        assert _codes(found) == ["double-donation"]

    def test_double_donation_survives_result_rebind(self, tmp_path):
        # `pool = g(pool, pool)` rebinds the result, but the CALL still
        # aliases one backing buffer into two donated positions
        found = _lint(tmp_path, """
            import jax

            def step(k_pool, v_pool):
                return k_pool, v_pool

            def serve(pool):
                g = jax.jit(step, donate_argnums=(0, 1))
                pool = g(pool, pool)
                return pool
            """, ["donation"])
        assert _codes(found) == ["double-donation"]

    def test_donated_reentry_into_second_jit(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            def step(state, x):
                return state

            def other(state):
                return state

            def run(state, x):
                g = jax.jit(step, donate_argnums=(0,))
                h = jax.jit(other)
                out = g(state, x)
                return h(state)
            """, ["donation"])
        assert _codes(found) == ["donated-reentry"]

    def test_pragma_suppresses(self, tmp_path):
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(params, lr):
                return params

            f = jax.jit(step)  # lint: allow(missing-donation)
            """, ["donation"])
        assert found == []


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

class TestRetraceHazardPass:
    def test_unbucketed_shape_key(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def build(prompt_ids, f):
                key = (len(prompt_ids), 4)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert _codes(found) == ["unbucketed-shape-key"]

    def test_bucketed_key_is_quiet(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def bucket_for(n):
                return 1 << n.bit_length()

            def build(prompt_ids, f):
                b = bucket_for(len(prompt_ids))
                key = (b, 4)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert found == []

    def test_shape_unpack_into_key(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def build(input_ids, f):
                B, P = input_ids.shape
                key = (B, P)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert _codes(found) == ["unbucketed-shape-key",
                                 "unbucketed-shape-key"]

    def test_computed_float_key(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def build(scale, f):
                s = scale * 2
                key = (float(s),)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert _codes(found) == ["float-cache-key"]

    def test_canonicalized_knob_float_is_quiet(self, tmp_path):
        # float(<plain parameter>) is the generate()-style bounded knob
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def build(temperature, f):
                key = (float(temperature), 4)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert found == []

    def test_unordered_key_part(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def build(names, f):
                key = (tuple(set(names)),)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert _codes(found) == ["unordered-key-part"]

    def test_sorted_set_is_quiet(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def build(names, f):
                key = (tuple(sorted(set(names))),)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert found == []

    def test_uncached_inline_jit_call(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            def run(f, x):
                return jax.jit(f)(x)
            """, ["retrace-hazard"])
        assert _codes(found) == ["uncached-jit-call"]

    def test_data_dependent_static_arg(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            def f(x, n):
                return x[:n]

            def run(ids, x):
                g = jax.jit(f, static_argnums=(1,))
                return g(x, len(ids))
            """, ["retrace-hazard"])
        assert _codes(found) == ["unbucketed-shape-key"]
        assert "static arg" in found[0].message

    def test_finding_carries_wrap_surface_label(self, tmp_path):
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.observability.compilestats import wrap

            cache = {}

            def build(prompt_ids, f):
                key = (len(prompt_ids),)
                cache[key] = wrap(jax.jit(f), "serving.decode_chunk",
                                  budget=1)
            """, ["retrace-hazard"])
        assert len(found) == 1
        assert found[0].detail.startswith("serving.decode_chunk:")
        assert "[surface=serving.decode_chunk]" in found[0].message

    def test_pragma_suppresses(self, tmp_path):
        found = _lint(tmp_path, """
            import jax

            cache = {}

            def build(prompt_ids, f):
                key = (len(prompt_ids), 4)  # lint: allow(unbucketed-shape-key)
                cache[key] = jax.jit(f)
            """, ["retrace-hazard"])
        assert found == []


class TestSurfaceVocabulary:
    """Static retrace findings and runtime pt_compile_* telemetry must
    share one surface-name vocabulary (the acceptance criterion)."""

    @staticmethod
    def _wrap_literals_in_tree():
        """Every surface-name string the source passes to
        compilestats.wrap (directly, via _tracked, or through a
        *_SURFACE module constant)."""
        ctx = make_context()
        literals = set()
        for mod in ctx.index.iter_modules():
            for n in ast.walk(mod.tree):
                if isinstance(n, ast.Call):
                    term = n.func.attr if isinstance(
                        n.func, ast.Attribute) else (
                        n.func.id if isinstance(n.func, ast.Name)
                        else None)
                    if term in ("wrap", "_tracked", "_wrap"):
                        for a in list(n.args) + \
                                [kw.value for kw in n.keywords]:
                            for c in ast.walk(a):
                                if isinstance(c, ast.Constant) and \
                                        isinstance(c.value, str) and \
                                        "." in c.value:
                                    literals.add(c.value)
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and n.targets[0].id.endswith("_SURFACE") \
                        and isinstance(n.value, ast.Constant) \
                        and isinstance(n.value.value, str):
                    literals.add(n.value.value)
        return literals

    def test_compile_surfaces_mirror_wrap_sites(self):
        lits = self._wrap_literals_in_tree()
        declared = set(allowlist.COMPILE_SURFACES)
        assert declared == lits, (
            "COMPILE_SURFACES (analysis/allowlist.py) must mirror the "
            "compilestats.wrap call sites exactly — "
            f"missing from allowlist: {sorted(lits - declared)}, "
            f"stale in allowlist: {sorted(declared - lits)}")

    def test_runtime_compile_registry_uses_declared_labels(self):
        """Run one tiny generate(): the surface it registers in the
        runtime compilestats registry must be a declared label."""
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.models import GPTForPretraining, gpt3_tiny
        from paddle_tpu.observability import compilestats
        paddle.seed(0)
        net = GPTForPretraining(gpt3_tiny())
        net.generate(paddle.to_tensor(
            np.asarray([[1, 2, 3]], dtype="int32")), max_new_tokens=2)
        assert "generation.decode" in compilestats.surfaces()
        assert "generation.decode" in allowlist.COMPILE_SURFACES

    def test_surface_labels_fallback_points_at_declared_names(self):
        for (_rel, _qual), label in allowlist.SURFACE_LABELS.items():
            assert label in allowlist.COMPILE_SURFACES


# ---------------------------------------------------------------------------
# concurrency
# ---------------------------------------------------------------------------

def _conc(tmp_path, code, monkeypatch, declared=None, safe=None):
    name = "fixture.py"
    monkeypatch.setattr(allowlist, "CONCURRENCY_MODULES",
                        allowlist.CONCURRENCY_MODULES + (name,))
    # the pass imported the tuple by value — patch its module too
    from paddle_tpu.analysis import concurrency as conc_mod
    monkeypatch.setattr(conc_mod, "CONCURRENCY_MODULES",
                        conc_mod.CONCURRENCY_MODULES + (name,))
    for key, meta in (declared or {}).items():
        monkeypatch.setitem(allowlist.CONCURRENT_CLASSES,
                            (name, key), meta)
    for key, reason in (safe or {}).items():
        monkeypatch.setitem(allowlist.THREAD_SAFE_STATE,
                            (name, key), reason)
    return _lint(tmp_path, code, ["concurrency"], name=name)


class TestConcurrencyPass:
    THREADED = """
        import threading

        class Box:
            def __init__(self):
                self._items = []
                self._lock = threading.Lock()

            def start(self):
                t = threading.Thread(target=self._work, daemon=True)
                t.start()

            def _work(self):
                {work}

            def drain(self):
                {drain}
    """

    def test_unguarded_thread_mutation_flagged(self, tmp_path,
                                               monkeypatch):
        found = _conc(tmp_path, self.THREADED.format(
            work="self._items.append(1)",
            drain="return self._items.pop() if self._items else None"),
            monkeypatch)
        codes = _codes(found)
        assert codes.count("unguarded-shared-mutation") == 2  # both sides

    def test_lock_guarded_is_quiet(self, tmp_path, monkeypatch):
        found = _conc(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._items = []
                    self._lock = threading.Lock()

                def start(self):
                    t = threading.Thread(target=self._work, daemon=True)
                    t.start()

                def _work(self):
                    with self._lock:
                        self._items.append(1)

                def drain(self):
                    with self._lock:
                        if self._items:
                            return self._items.pop()
                    return None
            """, monkeypatch)
        assert found == []

    def test_check_then_act_flagged(self, tmp_path, monkeypatch):
        found = _conc(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._free = [1, 2]

                def start(self):
                    t = threading.Thread(target=self._work, daemon=True)
                    t.start()

                def _work(self):
                    with self._lock:
                        self._free.append(3)

                def take(self):
                    if self._free:
                        return self._free.pop()
                    return None
            """, monkeypatch)
        codes = _codes(found)
        assert "check-then-act" in codes

    def test_lock_only_the_act_still_flagged(self, tmp_path,
                                             monkeypatch):
        # the natural WRONG fix: leaving the check outside the lock —
        # two threads both pass `if self._free:` with one element left
        found = _conc(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._free = [1, 2]
                    self._lock = threading.Lock()

                def start(self):
                    t = threading.Thread(target=self._work, daemon=True)
                    t.start()

                def _work(self):
                    with self._lock:
                        self._free.append(3)

                def take(self):
                    if self._free:
                        with self._lock:
                            return self._free.pop()
                    return None
            """, monkeypatch)
        assert "check-then-act" in _codes(found)

    def test_thread_confined_state_is_quiet(self, tmp_path, monkeypatch):
        # no second root ever touches the attr -> not shared
        found = _conc(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._n = 0

                def start(self):
                    t = threading.Thread(target=self._work, daemon=True)
                    t.start()

                def _work(self):
                    self._n += 1
            """, monkeypatch)
        assert found == []

    def test_declared_concurrent_class_without_threads(self, tmp_path,
                                                       monkeypatch):
        # the FCFSScheduler shape: no Thread() in the file, contract
        # declared via CONCURRENT_CLASSES
        code = """
            class Sched:
                def __init__(self):
                    self._queue = []

                def submit(self, item):
                    self._queue.append(item)

                def admit(self):
                    if self._queue:
                        return self._queue.pop()
                    return None
            """
        # undeclared, the file is quiet (no thread entry points)...
        from paddle_tpu.analysis import concurrency as conc_mod
        monkeypatch.setattr(conc_mod, "CONCURRENCY_MODULES",
                            conc_mod.CONCURRENCY_MODULES
                            + ("fixture.py",))
        assert _lint(tmp_path, code, ["concurrency"],
                     name="fixture.py") == []
        # ...declared, the contract is enforced
        found = _conc(tmp_path, code, monkeypatch,
                      declared={"Sched": {"entries": ["submit"],
                                          "reason": "router threads"}})
        codes = _codes(found)
        assert codes.count("unguarded-shared-mutation") == 2
        assert "check-then-act" in codes

    def test_per_key_dict_cells_do_not_alias(self, tmp_path,
                                             monkeypatch):
        # submit touches stats["a"], the owner loop touches stats["b"]:
        # different cells, only the cross-thread key is hot
        found = _conc(tmp_path, """
            class Eng:
                def __init__(self):
                    self.stats = {"a": 0, "b": 0}

                def submit(self):
                    self.stats["a"] += 1

                def step(self):
                    self.stats["b"] += 1
                    return self.stats["a"]
            """, monkeypatch,
            declared={"Eng": {"entries": ["submit"], "reason": "x"}})
        assert _codes(found) == ["unguarded-shared-mutation"]
        assert 'stats[\'a\']' in found[0].detail

    def test_thread_safe_state_allowlist(self, tmp_path, monkeypatch):
        found = _conc(tmp_path, self.THREADED.format(
            work="self._items.append(1)",
            drain="return list(self._items)"),
            monkeypatch,
            safe={"Box._items": "GIL-atomic append; reader snapshots"})
        assert found == []

    def test_module_global_mutation_from_thread(self, tmp_path,
                                                monkeypatch):
        found = _conc(tmp_path, """
            import threading

            _REG = {}

            def loop():
                _REG["x"] = 1

            def start():
                threading.Thread(target=loop, daemon=True).start()

            def read():
                return _REG.get("x")
            """, monkeypatch)
        assert _codes(found) == ["unguarded-shared-mutation"]
        assert "<module>._REG" in found[0].detail

    def test_pragma_suppresses(self, tmp_path, monkeypatch):
        found = _conc(tmp_path, self.THREADED.format(
            work="self._items.append(1)  # lint: allow(concurrency)",
            drain="return list(self._items)"),
            monkeypatch)
        assert found == []

    def test_real_scheduler_and_engine_are_clean(self):
        found = run_passes(
            paths=[os.path.join(REPO_ROOT, "paddle_tpu", "inference")],
            passes=["concurrency"])
        assert found == [], found

    def test_scheduler_lock_actually_guards(self):
        """Runtime spot check of the fix: concurrent submits against a
        draining scheduler lose no request and never corrupt the
        free-list."""
        import threading
        from paddle_tpu.inference.scheduler import FCFSScheduler
        sched = FCFSScheduler(num_slots=4)
        N, workers = 200, 4
        errs = []

        def submitter(k):
            try:
                for i in range(N):
                    sched.submit([1, 2, 3], 4)
            except Exception as e:            # pragma: no cover
                errs.append(e)
        ts = [threading.Thread(target=submitter, args=(k,))
              for k in range(workers)]
        drained = 0
        for t in ts:
            t.start()
        while any(t.is_alive() for t in ts) or sched.queue_depth:
            for _req, slot in sched.admissions():
                sched.release(slot)
                drained += 1
        for t in ts:
            t.join()
        assert not errs
        assert drained == N * workers
        assert sorted(sched._free) == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# runner integration: listing, baseline, self-lint, --changed-only
# ---------------------------------------------------------------------------

class TestRunnerIntegration:
    def test_new_passes_in_default_registry(self):
        with pytest.raises(ValueError) as ei:
            run_passes(passes=["no-such-pass"])
        msg = str(ei.value)
        for name in NEW_PASSES:
            assert name in msg

    def test_list_passes_cli(self):
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis",
             "--list-passes"], capture_output=True, text=True,
            cwd=REPO_ROOT)
        assert out.returncode == 0
        for name in NEW_PASSES:
            assert name in out.stdout.split()

    def test_self_lint_new_passes_clean(self):
        """The tree must be CLEAN under the three new passes with the
        baseline still empty — every finding was fixed or pragma'd
        (the acceptance criterion), none baselined."""
        found = run_passes(passes=NEW_PASSES)
        assert found == [], found
        baseline = load_baseline(os.path.join(
            REPO_ROOT, "tools", "lint_baseline.json"))
        assert baseline == {}, "lint_baseline.json must stay EMPTY"

    def test_baseline_interplay(self, tmp_path):
        (tmp_path / "fx.py").write_text(textwrap.dedent("""
            import jax
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(params, lr):
                return params

            f = jax.jit(step)
            """))
        found = run_passes(paths=[str(tmp_path)], passes=["donation"])
        assert len(found) == 1
        bl = tmp_path / "bl.json"
        write_baseline(str(bl), found)
        new, old = split_new(found, load_baseline(str(bl)))
        assert new == [] and len(old) == 1
        # a second, distinct finding is NOT covered
        (tmp_path / "fx.py").write_text(textwrap.dedent("""
            import jax
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(params, lr):
                return params

            @jit_surface
            def step2(opt_state, lr):
                return opt_state

            f = jax.jit(step)
            g = jax.jit(step2)
            """))
        found2 = run_passes(paths=[str(tmp_path)], passes=["donation"])
        new2, old2 = split_new(found2, load_baseline(str(bl)))
        assert len(new2) == 1 and len(old2) == 1

    def test_changed_only_scoped_run(self, monkeypatch, capsys):
        target = os.path.join(REPO_ROOT, "paddle_tpu", "analysis",
                              "base.py")
        monkeypatch.setattr(runner_mod, "git_changed_files",
                            lambda root: [target])
        rc = runner_mod.main(["--changed-only", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["new"] == 0

    def test_changed_only_empty_diff_is_green(self, monkeypatch,
                                              capsys):
        monkeypatch.setattr(runner_mod, "git_changed_files",
                            lambda root: [])
        rc = runner_mod.main(["--changed-only"])
        assert rc == 0
        assert "no changed" in capsys.readouterr().out

    def test_changed_only_rejects_explicit_paths(self, capsys):
        rc = runner_mod.main(["--changed-only", "paddle_tpu"])
        assert rc == 2

    def test_changed_only_finds_seeded_violation(self, tmp_path,
                                                 monkeypatch, capsys):
        # a changed file with a violation fails the scoped run
        fx = tmp_path / "fx.py"
        fx.write_text(textwrap.dedent("""
            import jax

            def run(f, x):
                return jax.jit(f)(x)
            """))
        monkeypatch.setattr(runner_mod, "git_changed_files",
                            lambda root: [str(fx)])
        rc = runner_mod.main(["--changed-only", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert any(f["code"] == "uncached-jit-call"
                   for f in out["findings"])


class TestPolicyIntegrity:
    def test_concurrency_modules_exist(self):
        ctx = make_context()
        for rel in allowlist.CONCURRENCY_MODULES:
            assert rel in ctx.index.by_relpath, rel

    def test_concurrent_class_declarations_resolve(self):
        ctx = make_context()
        for rel, cls in allowlist.CONCURRENT_CLASSES:
            mod = ctx.index.by_relpath.get(rel)
            assert mod is not None, rel
            if cls == "<module>":
                continue
            assert any(q.split(".")[0] == cls for q in mod.funcs), \
                (rel, cls)

    def test_thread_safe_state_entries_resolve(self):
        ctx = make_context()
        for rel, entry in allowlist.THREAD_SAFE_STATE:
            mod = ctx.index.by_relpath.get(rel)
            assert mod is not None, rel
            owner, attr = entry.split(".", 1)
            if owner == "<module>":
                assert attr in mod.source, (rel, entry)
            else:
                assert any(q.split(".")[0] == owner
                           for q in mod.funcs), (rel, entry)
                assert attr in mod.source, (rel, entry)
