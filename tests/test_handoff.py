"""Fault-tolerant disaggregated prefill/decode (inference/handoff.py):
the reserve -> transfer -> import -> arm protocol end to end, and its
failure ladder under chaos.

The parity contract is the real check: whatever the protocol does —
complete the handoff, or degrade to local re-prefill after a dropped
bundle, a flipped byte, a reservation timeout/expiry, or a prefill
replica dying mid-transfer — greedy output must match ``generate()``
token for token, every degradation must book exactly one
``handoff_fallback`` event, and every allocator must come out of the
run with ``check()`` clean (no leaked pages, no stuck reservations).
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.framework import failpoints, guardian
from paddle_tpu.inference import handoff, kvcache
from paddle_tpu.inference.router import ServingFleet
from paddle_tpu.observability import tracing
from paddle_tpu.models import GPTForPretraining, gpt3_tiny

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    return GPTForPretraining(gpt3_tiny())


@pytest.fixture(autouse=True)
def _clean():
    obs.enable(True)     # the elastic suite leaves the front door off
    obs.get_registry().reset()
    tracing.reset()
    guardian.clear_events()
    failpoints.clear()
    yield
    failpoints.clear()


def _gen(gpt, prompt, n):
    ids, _ = gpt.generate(paddle.to_tensor(prompt[None, :]),
                          max_new_tokens=n)
    return np.asarray(ids._value)[0]


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 1024, (n,)).astype("int32") for n in lens]


PROMPT_LENS = (5, 11, 8, 9)
BUDGET = 6


def _make_fleet(gpt):
    return ServingFleet(gpt, num_replicas=2, num_slots=2, chunk=4,
                        kv_mode="paged", page_size=8,
                        prefill_buckets=(8, 16, 32), max_seq_len=128,
                        roles=("prefill", "decode"), handoff_ttl_s=60.0)


@pytest.fixture(scope="module")
def pd_fleet(gpt):
    """Shared prefill+decode fleet (compiles once per module); tests
    ``reset()`` it and may shrink ``_handoff.ttl_s`` (restored there).
    Tests that KILL a replica must build their own — ``reset()``
    deliberately never revives the dead."""
    fleet = _make_fleet(gpt)
    yield fleet


@pytest.fixture(scope="module")
def refs(gpt):
    return [_gen(gpt, p, BUDGET)
            for p in _prompts(21, PROMPT_LENS)]


def _run(fleet, threads=False):
    reqs = [fleet.submit(p, BUDGET) for p in _prompts(21, PROMPT_LENS)]
    fleet.run(threads=threads, timeout=300)
    return reqs


def _assert_bitwise(reqs, refs):
    for r, ref in zip(reqs, refs):
        assert r.finish_reason in ("eos", "budget")
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      ref)


def _assert_clean(fleet):
    for rep in fleet.replicas:
        if rep.state == "up":
            assert rep.engine._kv.check()
            assert not rep.engine._kv._reservations


class TestDisaggregatedHappyPath:
    def test_bitwise_and_decode_never_prefills(self, gpt, pd_fleet,
                                               refs):
        """The tentpole contract: every fresh prompt prefills on the
        prefill replica, its KV crosses as a checksummed bundle, and
        the decode replica arms the slot WITHOUT running any prompt
        prefill — output bitwise-equal to ``generate()``."""
        pd_fleet.reset()
        reqs = _run(pd_fleet)
        _assert_bitwise(reqs, refs)
        _assert_clean(pd_fleet)
        stats = pd_fleet._handoff.snapshot()
        assert stats["transfers"] == len(reqs)
        assert stats["fallbacks"] == 0
        by_role = {r.role: r for r in pd_fleet.replicas}
        assert by_role["decode"].engine.stats["prefills"] == 0
        assert by_role["prefill"].engine.stats["prefills"] == len(reqs)
        evs = guardian.events("handoff_transfer")
        assert len(evs) == len(reqs)
        for e in evs:
            assert e["src"] == by_role["prefill"].idx
            assert e["dst"] == by_role["decode"].idx
            assert e["pages"] >= 1 and e["bytes"] > 0
        reg = obs.get_registry()
        assert reg.get("pt_handoff_transfers_total").value() == len(reqs)
        assert reg.get("pt_handoff_bytes_total").value() > 0

    def test_threaded_bitwise(self, gpt, pd_fleet, refs):
        pd_fleet.reset()
        reqs = _run(pd_fleet, threads=True)
        _assert_bitwise(reqs, refs)
        _assert_clean(pd_fleet)
        assert pd_fleet._handoff.snapshot()["transfers"] + \
            pd_fleet._handoff.snapshot()["fallbacks"] >= len(reqs)

    def test_roles_validation(self, gpt):
        with pytest.raises(ValueError, match="at least one"):
            ServingFleet(gpt, num_replicas=2, kv_mode="paged",
                         page_size=8, num_slots=2,
                         prefill_buckets=(8, 16), max_seq_len=64,
                         roles=("prefill", "prefill"))
        with pytest.raises(ValueError, match="paged"):
            ServingFleet(gpt, num_replicas=2, num_slots=2,
                         prefill_buckets=(8, 16),
                         roles=("prefill", "decode"))
        with pytest.raises(ValueError, match="all 2 replicas"):
            ServingFleet(gpt, num_replicas=2, kv_mode="paged",
                         page_size=8, num_slots=2,
                         prefill_buckets=(8, 16), max_seq_len=64,
                         roles=("prefill",))
        with pytest.raises(ValueError, match="unknown replica roles"):
            ServingFleet(gpt, num_replicas=2, kv_mode="paged",
                         page_size=8, num_slots=2,
                         prefill_buckets=(8, 16), max_seq_len=64,
                         roles=("prefill", "verify"))


@pytest.mark.chaos
class TestHandoffChaos:
    """Each failpoint drives one rung of the failure ladder; every rung
    must converge on bitwise output, one fallback event per degraded
    request, and zero leaked pages/reservations."""

    def _chaos(self, fleet, refs, fp, spec, ttl=None):
        fleet.reset()
        guardian.clear_events()
        old_ttl = fleet._handoff.ttl_s
        if ttl is not None:
            fleet._handoff.ttl_s = ttl
        failpoints.set_failpoint(fp, spec)
        try:
            reqs = _run(fleet)
        finally:
            failpoints.clear()
            fleet._handoff.ttl_s = old_ttl
        _assert_bitwise(reqs, refs)
        _assert_clean(fleet)
        falls = guardian.events("handoff_fallback")
        stats = fleet._handoff.snapshot()
        assert len(falls) == stats["fallbacks"]
        # exactly one degradation event per fallen-back request
        assert len({e["req_id"] for e in falls}) == len(falls)
        return reqs, falls, stats

    def test_drop_bundle_ttl_reclaims_reservation(self, gpt, pd_fleet,
                                                  refs):
        """Every bundle is lost in transit: reservations expire by TTL
        (no page leaks past the deadline), every request completes by
        local re-prefill on the decode replica."""
        reqs, falls, stats = self._chaos(
            pd_fleet, refs, "handoff.drop_bundle", "error", ttl=0.4)
        assert stats["transfers"] == 0
        assert stats["fallbacks"] == len(reqs)
        assert stats["reserve_expired"] == len(reqs)
        assert all(e["reason"] == "reserve_ttl_expired" for e in falls)
        reg = obs.get_registry()
        assert reg.get("pt_handoff_reserve_expired_total") \
            .value() == len(reqs)

    def test_corrupt_page_rejected_then_local_prefill(self, gpt,
                                                      pd_fleet, refs):
        """A flipped byte fails the per-page CRC at import: the bundle
        is rejected whole (pool untouched) and the SAME admission falls
        through to a local re-prefill — no retried import, no
        double-scatter."""
        reqs, falls, stats = self._chaos(
            pd_fleet, refs, "handoff.corrupt_page", "error*2")
        assert stats["fallbacks"] == 2
        assert stats["transfers"] == len(reqs) - 2
        assert all(e["reason"].startswith("import_rejected")
                   for e in falls)

    def test_reserve_timeout_retries_then_falls_back(self, gpt,
                                                     pd_fleet, refs):
        """The reserve phase exhausts its bounded retry budget: the
        protocol never starts and the request books a launch-time
        fallback (jittered-backoff attempts are metered)."""
        reqs, falls, stats = self._chaos(
            pd_fleet, refs, "handoff.reserve_timeout", "error")
        assert stats["launched"] == 0
        assert stats["fallbacks"] == len(reqs)
        assert stats["retries"] == 2 * len(reqs)   # 3 attempts each
        assert all(e["reason"] == "reserve_timeout" for e in falls)

    @pytest.mark.slow          # fresh fleet: pays its own compiles
    def test_prefill_crash_mid_transfer(self, gpt, refs):
        """The prefill replica dies INSIDE the capture window (bundle
        half-built): in-protocol requests degrade via stub-loss /
        heartbeat detection, later requests route straight to the
        decode replica — all complete bitwise on the survivor.
        Fresh fleet: the kill is permanent across ``reset()``."""
        fleet = _make_fleet(gpt)
        reqs, falls, stats = self._chaos(
            fleet, refs, "serving.prefill_crash", "error*1")
        assert fleet.stats["replica_deaths"] == 1
        assert stats["transfers"] == 0
        assert len(falls) == len(reqs)
        assert {e["reason"] for e in falls} <= {
            "prefill_replica_death", "no_prefill_replica"}
        assert "prefill_replica_death" in {e["reason"] for e in falls}

    def test_threaded_replica_crash_bitwise(self, gpt, refs):
        """Generic mid-decode replica crash through worker threads on
        the disaggregated fleet: whichever role dies, the survivor
        finishes everything bitwise with no leaked pages."""
        fleet = _make_fleet(gpt)
        failpoints.set_failpoint("serving.replica_crash", "error*1")
        try:
            reqs = _run(fleet, threads=True)
        finally:
            failpoints.clear()
        _assert_bitwise(reqs, refs)
        _assert_clean(fleet)
        assert fleet.stats["replica_deaths"] == 1


class TestBundleIntegrity:
    """Satellite: the checksummed-bundle contract at the allocator
    level — corrupt/torn bundles are rejected whole with the pool
    untouched, and a reservation ticket is strictly single-use."""

    def _managers(self):
        spec = [(2, 4), (2, 4)]
        a = kvcache.PagedKVManager(spec, 2, 32, 8, 9, "float32")
        b = kvcache.PagedKVManager(spec, 2, 32, 8, 9, "float32")
        prompt = np.arange(16, dtype=np.int32)
        a.bind(0, a.plan(prompt, 8, 8))
        return a, b

    def test_corrupt_bundle_rejected_whole_pool_untouched(self):
        a, b = self._managers()
        payload = a.export_pages(0)
        handoff._corrupt_one_page(payload)
        ticket = b.reserve_pages(len(payload["logical"]))
        pools_before = b.device_pools()
        with pytest.raises(kvcache.KVBundleError, match="checksum"):
            b.import_pages(1, payload, ticket=ticket)
        # rejected WHOLE: no page touched the pool, no mapping exists,
        # and the reservation survived (failure happened before the
        # ticket was consumed)
        assert b.device_pools() is pools_before
        assert not b._slot_pages[1]
        assert b.check()
        clean = a.export_pages(0)
        assert b.import_pages(1, clean, ticket=ticket) \
            == len(clean["logical"])
        assert b.check()

    def test_torn_bundle_rejected(self):
        a, b = self._managers()
        payload = a.export_pages(0)
        payload["layers"] = payload["layers"][:-1]       # torn in flight
        with pytest.raises(kvcache.KVBundleError):
            b.import_pages(1, payload)
        assert b.check() and not b._slot_pages[1]

    def test_reservation_ticket_single_use(self):
        """Exactly-once arming at the allocator: a consumed ticket can
        never import again (a retried import cannot double-scatter)."""
        a, b = self._managers()
        payload = a.export_pages(0)
        ticket = b.reserve_pages(len(payload["logical"]))
        b.import_pages(0, payload, ticket=ticket)
        with pytest.raises(KeyError, match="reservation"):
            b.import_pages(1, payload, ticket=ticket)
        assert b.check() and not b._slot_pages[1]
        # cancel after consumption is an idempotent no-op
        assert b.cancel_reservation(ticket) == 0

    def test_record_consume_gate_is_exactly_once(self, gpt, pd_fleet):
        """The coordinator half of exactly-once: ``consume()`` flips
        true exactly once, and only inside the arming window."""
        coord = pd_fleet._handoff
        req = type("R", (), {"req_id": "x"})()
        rec = handoff.HandoffRecord(coord, req, 0, 1, ticket=None,
                                    reserved=1, ttl_s=60.0)
        assert not rec.consume()             # still in transfer state
        rec.state = handoff._ARMING
        assert rec.consume()
        assert not rec.consume()             # second arm attempt loses


class TestHandoffObservability:
    def test_doctor_ranks_handoff_failure(self):
        from paddle_tpu.observability import doctor
        ev = doctor._empty_evidence()
        ev["guardian_events"] = [
            {"event": "handoff_fallback", "req_id": i,
             "reason": "reserve_ttl_expired", "dst": 1}
            for i in range(3)]
        d = doctor.diagnose(ev)
        assert d["verdict"] == "handoff_failure"
        top = d["diagnoses"][0]
        assert top["cause"] == "handoff_failure"
        assert top["score"] >= doctor._MIN_INCIDENT_SCORE
        assert any("fell back" in line for line in top["evidence"])


@pytest.mark.lint
class TestHandoffLintSelfCheck:
    def test_failpoints_registered(self):
        import paddle_tpu.inference.handoff  # noqa: F401 — registers
        names = failpoints.registered()
        for fp in ("handoff.drop_bundle", "handoff.corrupt_page",
                   "handoff.reserve_timeout", "serving.prefill_crash"):
            assert fp in names

    def test_handoff_concurrency_and_sync_lints_clean(self):
        """The coordinator's locked regions satisfy the concurrency
        pass and the module's zero-sync contract satisfies host-sync —
        with the committed baseline still EMPTY."""
        from paddle_tpu.analysis import runner
        findings = runner.run_passes(
            paths=["paddle_tpu/inference/handoff.py",
                   "paddle_tpu/inference/router.py",
                   "paddle_tpu/inference/serving.py",
                   "paddle_tpu/inference/kvcache.py"],
            passes=["concurrency", "host-sync"])
        assert findings == []
        import os
        base = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "lint_baseline.json")
        with open(base, encoding="utf-8") as f:
            assert not json.load(f)["findings"]      # baseline EMPTY
