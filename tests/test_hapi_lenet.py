"""M0 exit test (SURVEY.md §7.2): LeNet-MNIST via Model.fit."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.metric import Accuracy
from paddle_tpu.static import InputSpec
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet
from paddle_tpu.vision.transforms import ToTensor


def _make_model(jit=True):
    net = LeNet()
    model = paddle.Model(net, inputs=[InputSpec([None, 1, 28, 28],
                                                "float32", "image")],
                         labels=[InputSpec([None, 1], "int64", "label")])
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy(), jit=jit)
    return model


def test_lenet_fit_learns():
    paddle.seed(42)
    train = MNIST(mode="train", transform=ToTensor())
    model = _make_model(jit=True)
    model.fit(train, batch_size=256, epochs=2, verbose=0)
    logs = model.evaluate(MNIST(mode="train", transform=ToTensor()),
                          batch_size=256, verbose=0)
    # synthetic classes are separable; 2 epochs should beat 60%
    assert logs["acc"] > 0.6, logs


def test_train_batch_eager_vs_jit_agree():
    paddle.seed(0)
    x = np.random.rand(8, 1, 28, 28).astype("float32")
    y = np.random.randint(0, 10, (8, 1)).astype("int64")

    paddle.seed(7)
    m1 = _make_model(jit=True)
    loss1 = m1.train_batch([x], [y])

    paddle.seed(7)
    m2 = _make_model(jit=False)
    loss2 = m2.train_batch([x], [y])
    np.testing.assert_allclose(loss1[0][0], loss2[0][0], rtol=2e-4)


def test_predict_and_eval():
    model = _make_model()
    test = MNIST(mode="test", transform=ToTensor())
    out = model.predict(test, batch_size=128, stack_outputs=True)
    assert out[0].shape == (len(test), 10)


def test_model_save_load(tmp_path):
    model = _make_model()
    x = np.random.rand(4, 1, 28, 28).astype("float32")
    y = np.random.randint(0, 10, (4, 1)).astype("int64")
    model.train_batch([x], [y])
    path = str(tmp_path / "ckpt" / "model")
    model.save(path)

    model2 = _make_model()
    model2.load(path)
    p1 = model.predict_batch([x])[0]
    p2 = model2.predict_batch([x])[0]
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_summary():
    net = LeNet()
    info = paddle.summary(net, (1, 1, 28, 28))
    assert info["total_params"] == sum(
        int(np.prod(p.shape)) for p in net.parameters())
