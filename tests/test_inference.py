"""paddle.inference predictor over jit.save artifacts.

Reference analogue: test/legacy_test/test_inference_api.py +
inference C++ API tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec
from paddle_tpu.inference import Config, create_predictor, PrecisionType


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    net = SmallNet()
    net.eval()
    path = str(tmp_path_factory.mktemp("infer") / "model")
    paddle.jit.save(net, path,
                    input_spec=[InputSpec([1, 8], "float32", name="x")])
    x = np.random.RandomState(0).randn(1, 8).astype("float32")
    ref = net(paddle.to_tensor(x)).numpy()
    return path, x, ref


class TestConfig:
    def test_knobs(self):
        c = Config("some/model")
        assert c.prog_file() == "some/model.pdmodel"
        assert c.params_file() == "some/model.pdiparams"
        c.enable_use_gpu(100, 0, PrecisionType.Half)
        assert c.use_gpu()
        c.disable_gpu()
        assert not c.use_gpu()
        c.switch_ir_optim(False)
        assert not c.ir_optim()
        assert "device" in c.summary()

    def test_pdmodel_suffix_stripped(self):
        c = Config("some/model.pdmodel")
        assert c.prog_file() == "some/model.pdmodel"


class TestPredictor:
    def test_zero_copy_run(self, saved_model):
        path, x, ref = saved_model
        config = Config(path)
        config.disable_gpu()
        pred = create_predictor(config)
        names = pred.get_input_names()
        assert names == ["x"]
        h = pred.get_input_handle("x")
        h.copy_from_cpu(x)
        pred.run()
        out_names = pred.get_output_names()
        assert len(out_names) == 1
        out = pred.get_output_handle(out_names[0]).copy_to_cpu()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_feed_list_run(self, saved_model):
        path, x, ref = saved_model
        config = Config(path)
        config.disable_gpu()
        pred = create_predictor(config)
        outs = pred.run([x])
        np.testing.assert_allclose(outs[0], ref, rtol=1e-4, atol=1e-5)

    def test_missing_input_raises(self, saved_model):
        path, _, _ = saved_model
        config = Config(path)
        config.disable_gpu()
        pred = create_predictor(config)
        with pytest.raises(RuntimeError):
            pred.run()
