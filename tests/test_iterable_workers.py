"""Multiprocess IterableDataset workers (reference:
python/paddle/io/dataloader/worker.py _DatasetKind.ITER — each worker
iterates its own dataset copy with worker_info(id, num_workers) set, so
datasets shard themselves by worker id; unsharded datasets replicate).
"""
import os

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, IterableDataset, get_worker_info


class ShardedRange(IterableDataset):
    """Yields its slice of range(n) based on get_worker_info()."""

    def __init__(self, n):
        self.n = n

    def __iter__(self):
        info = get_worker_info()
        wid = info.id if info is not None else 0
        nw = info.num_workers if info is not None else 1
        for i in range(wid, self.n, nw):
            yield np.asarray([i], dtype=np.float32)


class UnshardedRange(IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.asarray([i], dtype=np.float32)


def _values(loader):
    out = []
    for batch in loader:
        out.extend(int(v) for v in np.asarray(batch._value).ravel())
    return out


class TestIterableMultiProcess:
    def test_sharded_dataset_covers_all_data_once(self):
        # the r4 threaded path ran ONE producer claiming worker 0 of N,
        # silently dropping the other shards — the regression this guards
        loader = DataLoader(ShardedRange(100), batch_size=5, num_workers=3)
        vals = _values(loader)
        assert sorted(vals) == list(range(100))

    def test_unsharded_dataset_replicates_per_worker(self):
        # reference semantics: each worker iterates its own full copy
        loader = DataLoader(UnshardedRange(20), batch_size=4, num_workers=2)
        vals = _values(loader)
        assert len(vals) == 40
        assert sorted(set(vals)) == list(range(20))
        assert all(vals.count(v) == 2 for v in range(20))

    def test_round_robin_order_is_deterministic(self):
        loader1 = _values(DataLoader(ShardedRange(60), batch_size=5,
                                     num_workers=2))
        loader2 = _values(DataLoader(ShardedRange(60), batch_size=5,
                                     num_workers=2))
        assert loader1 == loader2
        # worker 0's first batch (evens) precedes worker 1's (odds)
        assert loader1[:5] == [0, 2, 4, 6, 8]
        assert loader1[5:10] == [1, 3, 5, 7, 9]

    def test_drop_last_applies_per_worker(self):
        # 2 workers over 25 items: shards of 13 and 12; batch 4 ->
        # worker0 drops 1 leftover (12 kept), worker1 keeps 12 = 24 items
        loader = DataLoader(ShardedRange(25), batch_size=4, num_workers=2,
                            drop_last=True)
        vals = _values(loader)
        assert len(vals) == 24

    def test_uneven_exhaustion(self):
        # worker 1 of 4 over range(10) yields 2 items far fewer than
        # worker 0; remaining workers keep delivering after it drops out
        loader = DataLoader(ShardedRange(10), batch_size=1, num_workers=4)
        assert sorted(_values(loader)) == list(range(10))

    def test_worker_exception_surfaces(self):
        class Bad(IterableDataset):
            def __iter__(self):
                yield np.zeros(1, np.float32)
                raise ValueError("boom in iterable worker")

        with pytest.raises(RuntimeError, match="boom in iterable worker"):
            for _ in DataLoader(Bad(), batch_size=1, num_workers=2):
                pass

    def test_worker_init_fn_runs_per_worker(self):
        # init fn runs inside each subprocess; make its effect observable
        # through what the dataset yields
        class EnvEcho(IterableDataset):
            def __iter__(self):
                yield np.asarray([int(os.environ.get("PT_TEST_WID", -1))],
                                 dtype=np.float32)

        def init_fn(wid):
            os.environ["PT_TEST_WID"] = str(wid)

        loader = DataLoader(EnvEcho(), batch_size=1, num_workers=3,
                            worker_init_fn=init_fn)
        assert sorted(_values(loader)) == [0, 1, 2]

    def test_early_break_shuts_down_cleanly(self):
        import gc
        import multiprocessing as mp
        import threading as _threading
        import time
        before = _threading.active_count()
        for _ in range(3):
            loader = DataLoader(ShardedRange(1000), batch_size=2,
                                num_workers=2)
            for i, _ in enumerate(loader):
                if i == 1:
                    break
        gc.collect()
        deadline = time.monotonic() + 5.0
        while ((_threading.active_count() > before + 1
                or mp.active_children())
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert _threading.active_count() <= before + 1
        assert not mp.active_children()

    def test_threaded_fallback_matches_fork_semantics(self, monkeypatch):
        # force the fork-less path the way it really fails:
        # multiprocessing.get_context("fork") raises ValueError on
        # spawn-only platforms
        import paddle_tpu.io.worker as worker_mod

        class NoFork:
            def __init__(self, *a, **k):
                raise ValueError("cannot find context for 'fork'")

        monkeypatch.setattr(worker_mod, "IterableMultiProcessIter", NoFork)
        loader = DataLoader(ShardedRange(60), batch_size=5, num_workers=2)
        vals = _values(loader)
        assert sorted(vals) == list(range(60))
        assert vals[:5] == [0, 2, 4, 6, 8]
        assert vals[5:10] == [1, 3, 5, 7, 9]

    def test_threaded_fallback_exception_and_drop_last(self, monkeypatch):
        import paddle_tpu.io.worker as worker_mod

        class NoFork:
            def __init__(self, *a, **k):
                raise ValueError("cannot find context for 'fork'")

        monkeypatch.setattr(worker_mod, "IterableMultiProcessIter", NoFork)
        vals = _values(DataLoader(ShardedRange(25), batch_size=4,
                                  num_workers=2, drop_last=True))
        assert len(vals) == 24

        class Bad(IterableDataset):
            def __iter__(self):
                raise ValueError("boom threaded")
                yield

        with pytest.raises(ValueError, match="boom threaded"):
            for _ in DataLoader(Bad(), batch_size=1, num_workers=2):
                pass

    def test_batch_size_none_passes_samples_through(self):
        # auto-batching disabled: samples yielded bare, no collation
        vals = [int(np.asarray(s).ravel()[0])
                for s in DataLoader(UnshardedRange(10), batch_size=None,
                                    num_workers=0)]
        assert vals == list(range(10))
        # with workers it rides the threaded path (per-sample, replicated)
        vals = [int(np.asarray(s).ravel()[0])
                for s in DataLoader(UnshardedRange(10), batch_size=None,
                                    num_workers=2)]
        assert sorted(vals) == sorted(list(range(10)) * 2)

    def test_threaded_fallback_replicates_shared_iterator_dataset(
            self, monkeypatch):
        # ADVICE r5: a dataset whose __iter__ returns a SHARED stateful
        # iterator (returns self) used to be raced across the N producer
        # threads into arbitrary splits; the fork path replicates the
        # dataset per worker, so the threaded fallback must deep-copy to
        # match (each worker sees the full sequence)
        import paddle_tpu.io.worker as worker_mod

        class NoFork:
            def __init__(self, *a, **k):
                raise ValueError("cannot find context for 'fork'")

        class SharedIter(IterableDataset):
            def __init__(self, n):
                self.n = n
                self._it = None

            def __iter__(self):
                if self._it is None:
                    self._it = iter(range(self.n))
                return self

            def __next__(self):
                i = next(self._it)
                return np.asarray([i], dtype=np.float32)

        class StoredIter(IterableDataset):
            # the sneakier raced shape: __iter__ returns a stored
            # iterator rather than self
            def __init__(self, n):
                self._it = iter([np.asarray([i], dtype=np.float32)
                                 for i in range(n)])

            def __iter__(self):
                return self._it

        class FreshPlain(IterableDataset):
            # plain-function __iter__ that mints fresh iterators: safe
            # WITHOUT copying — must keep the zero-copy path (a big
            # in-memory dataset must not be duplicated per thread)
            copies = 0

            def __init__(self, n):
                self.records = [np.asarray([i], dtype=np.float32)
                                for i in range(n)]

            def __deepcopy__(self, memo):
                FreshPlain.copies += 1
                return self

            def __iter__(self):
                return iter(self.records)

        monkeypatch.setattr(worker_mod, "IterableMultiProcessIter", NoFork)
        for ds_cls in (SharedIter, StoredIter, FreshPlain):
            vals = _values(DataLoader(ds_cls(30), batch_size=5,
                                      num_workers=2))
            # replication semantics: every element exactly once PER worker
            assert sorted(vals) == sorted(list(range(30)) * 2), ds_cls
        assert FreshPlain.copies == 0, "fresh-iterator dataset was copied"

        # the needs-copy probe (2 extra __iter__ calls) runs at most
        # once per LOADER, not once per epoch
        class Counting(FreshPlain):
            calls = 0

            def __iter__(self):
                Counting.calls += 1
                return iter(self.records)

        loader = DataLoader(Counting(10), batch_size=5, num_workers=2)
        for _ in range(2):
            assert len(_values(loader)) == 20
        # 2 probe calls + 2 workers x 2 epochs
        assert Counting.calls == 6, Counting.calls

    def test_threaded_fallback_early_break_retires_producers(
            self, monkeypatch):
        import gc
        import threading as _threading
        import time
        import paddle_tpu.io.worker as worker_mod

        class NoFork:
            def __init__(self, *a, **k):
                raise ValueError("cannot find context for 'fork'")

        monkeypatch.setattr(worker_mod, "IterableMultiProcessIter", NoFork)
        before = _threading.active_count()
        for _ in range(3):
            loader = DataLoader(ShardedRange(10000), batch_size=2,
                                num_workers=2)
            for i, _ in enumerate(loader):
                if i == 1:
                    break
        gc.collect()  # abandoned generators run their finally -> stop.set()
        deadline = time.monotonic() + 5.0
        while (_threading.active_count() > before
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert _threading.active_count() <= before

    def test_timeout_fork_and_threaded(self, monkeypatch):
        import time

        class Hang(IterableDataset):
            def __iter__(self):
                yield np.zeros(1, np.float32)
                time.sleep(60)
                yield np.zeros(1, np.float32)

        loader = DataLoader(Hang(), batch_size=2, num_workers=1, timeout=0.5)
        with pytest.raises(TimeoutError):
            for _ in loader:
                pass

        import paddle_tpu.io.worker as worker_mod

        class NoFork:
            def __init__(self, *a, **k):
                raise ValueError("cannot find context for 'fork'")

        monkeypatch.setattr(worker_mod, "IterableMultiProcessIter", NoFork)
        loader = DataLoader(Hang(), batch_size=2, num_workers=1, timeout=0.5)
        with pytest.raises(TimeoutError):
            for _ in loader:
                pass
