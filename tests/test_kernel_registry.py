"""Kernel registry + fused-kernel dispatch (ISSUE 15).

Covers the `ops/registry.py` policy layer (platform selection, env /
``sdp_kernel`` overrides, interpret mode, the block-size autotune
table + cached micro-sweep), the attention dispatch ladder (padding so
S need not be a multiple of 512, the key-bias mask path, constraint
fallbacks), compilestats tracking of standalone kernel dispatches, and
the dense-vs-flash TRAIN-STEP gradient parity suite (GPT causal /
LLaMA rope+GQA / BERT additive-mask) in interpret mode.

Tolerance contract (docs/kernels.md "Numerics"): fp32 interpret-mode
flash vs the XLA dense path — forward within atol/rtol 2e-3, gradients
within 5e-3 relative-max; the XLA fallback paths are the dense math
itself and therefore bitwise.
"""
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.ops import registry as kreg


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    for var in ("PADDLE_TPU_ATTN_IMPL", "PADDLE_TPU_KERNEL_INTERPRET",
                "PADDLE_TPU_KERNEL_ATTENTION", "PADDLE_TPU_KERNEL_XENT",
                "PADDLE_TPU_FLASH_BLOCKS"):
        monkeypatch.delenv(var, raising=False)
    kreg._reset_for_tests()
    yield
    kreg._reset_for_tests()


def _dense_sdpa(q, k, v, mask=None, causal=False):
    from paddle_tpu.nn.functional.attention import _xla_attention
    return _xla_attention(q, k, v, mask=mask, causal=causal)


# ---------------------------------------------------------------------------
# selection policy
# ---------------------------------------------------------------------------

class TestChoose:
    def test_cpu_defaults_to_xla(self):
        sel = kreg.choose("attention")
        assert sel.impl == "xla" and not sel.forced and not sel.interpret

    def test_interpret_mode_selects_pallas(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        sel = kreg.choose("attention")
        assert sel.impl == "pallas" and sel.interpret and not sel.forced

    def test_legacy_attn_env_spellings(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_IMPL", "dense")
        assert kreg.choose("attention").impl == "xla"
        monkeypatch.setenv("PADDLE_TPU_ATTN_IMPL", "flash")
        # forcing the off-platform Pallas impl without interpret mode
        # would dispatch an uncompilable kernel: platform default wins
        sel = kreg.choose("attention")
        assert sel.impl == "xla" and not sel.forced
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        sel = kreg.choose("attention")
        assert sel.impl == "pallas" and sel.forced and sel.interpret

    def test_generic_kernel_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        monkeypatch.setenv("PADDLE_TPU_KERNEL_ATTENTION", "xla")
        sel = kreg.choose("attention")
        assert sel.impl == "xla" and sel.forced
        monkeypatch.setenv("PADDLE_TPU_KERNEL_XENT", "xla")
        assert kreg.choose("xent").impl == "xla"

    def test_force_context_nests_and_restores(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        with kreg.force("attention", "xla"):
            assert kreg.choose("attention").impl == "xla"
            with kreg.force("attention", "pallas"):
                assert kreg.choose("attention").impl == "pallas"
            assert kreg.choose("attention").impl == "xla"
        assert kreg.choose("attention").impl == "pallas"  # interpret dflt

    def test_typo_forced_impl_falls_back(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_KERNEL_ATTENTION", "no_such_impl")
        assert kreg.choose("attention").impl == "xla"

    def test_unknown_kernel_raises(self):
        with pytest.raises(KeyError):
            kreg.choose("no_such_kernel")

    def test_tpu_platform_selects_pallas_compiled(self):
        sel = kreg.choose("attention", platform="tpu")
        assert sel.impl == "pallas" and not sel.interpret

    def test_sdp_kernel_context_forces(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        with F.sdp_kernel(enable_flash=False):
            assert kreg.choose("attention").impl == "xla"
        with F.sdp_kernel(enable_math=False):
            assert kreg.choose("attention").impl == "pallas"
        assert not kreg.choose("attention").forced

    def test_selects_counter_books(self):
        reg = paddle.observability.get_registry()
        before = reg.get("pt_kernel_selects_total")
        base = before.value(kernel="attention", impl="xla") if before else 0
        kreg.choose("attention")
        m = reg.get("pt_kernel_selects_total")
        assert m.value(kernel="attention", impl="xla") == base + 1


# ---------------------------------------------------------------------------
# autotune table
# ---------------------------------------------------------------------------

class TestAutotune:
    def test_builtin_measured_entries(self):
        assert kreg.flash_blocks(4096, 64) == (512, 512)
        assert kreg.flash_blocks(1024, 64) == (256, 256)
        # heuristic fallback for shapes the table does not cover
        assert kreg.flash_blocks(2560, 96) == (256, 256)

    def test_env_override_and_divisibility_guard(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCKS", "128,128")
        assert kreg.flash_blocks(1024, 64) == (128, 128)
        monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCKS", "768,768")
        with pytest.warns(RuntimeWarning):
            bq, bk = kreg.flash_blocks(1024, 64)
        assert (bq, bk) == (256, 256)   # table answer, override ignored

    def test_micro_sweep_populates_and_persists(self, tmp_path):
        res = kreg.autotune_flash(256, 32, heads=2, batch=1,
                                  candidates=((128, 128), (256, 256)),
                                  iters=1, interpret=True)
        assert res["best"]["block_q"] in (128, 256)
        assert set(res["candidates"]) == {"128,128", "256,256"}
        # the sweep's winner now answers flash_blocks for that key
        assert kreg.flash_blocks(256, 32, 2) == (
            res["best"]["block_q"], res["best"]["block_k"])
        # ... and survives a fresh process (simulated by dropping the
        # in-memory table): the JSON cache is the durable copy
        cache = json.load(open(kreg.autotune_cache_path()))
        assert "256,32,2" in cache["entries"]
        kreg._reset_for_tests()
        assert kreg.flash_blocks(256, 32, 2) == (
            res["best"]["block_q"], res["best"]["block_k"])

    def test_sweep_key_folds_batch_into_heads(self):
        # dispatch looks blocks up at the FOLDED head count
        # (_fwd_blocks(S, D, B*H)); a batch>1 sweep must land its
        # winner on that key, not on the unfolded ``heads``
        res = kreg.autotune_flash(256, 32, heads=2, batch=2,
                                  candidates=((128, 128),),
                                  iters=1, interpret=True)
        assert tuple(res["key"]) == (256, 32, 4)
        assert kreg.flash_blocks(256, 32, 4) == (128, 128)
        # the unfolded key stays unpopulated (heuristic answers)
        assert kreg.flash_blocks(256, 32, 2) == (256, 256)

    def test_blocks_always_divide_s(self):
        # the must-divide-S contract covers the LAST-resort fallback
        # too: direct callers (incubate flash_attention gates on
        # S % 128 == 0 only) can present S = 640, and a non-dividing
        # answer makes the kernel silently skip the key tail
        for S in (640, 384, 1152, 100):
            bq, bk = kreg.flash_blocks(S, 64)
            assert S % bq == 0 and S % bk == 0, (S, bq, bk)

    def test_s640_kernel_matches_dense(self):
        # the S=640 shape that used to get (512,512): rows 512+ were
        # never written.  interpret mode, vs the dense reference
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_fwd)
        rng = np.random.RandomState(3)
        q, k, v = (jnp.asarray(rng.randn(1, 640, 2, 64).astype("f4"))
                   for _ in range(3))
        o = flash_attention_fwd(q, k, v, causal=True, interpret=True)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(64)
        mask = jnp.tril(jnp.ones((640, 640), bool))
        s = jnp.where(mask, s, -jnp.inf)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_zero_block_override_warns_not_crashes(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCKS", "256,0")
        with pytest.warns(RuntimeWarning):
            assert kreg.flash_blocks(1024, 64) == (256, 256)

    def test_torn_cache_is_skipped(self, tmp_path):
        with open(kreg.autotune_cache_path(), "w") as f:
            f.write("{not json")
        assert kreg.flash_blocks(1024, 64) == (256, 256)


# ---------------------------------------------------------------------------
# compilestats tracking
# ---------------------------------------------------------------------------

class TestTrackedKernel:
    def test_standalone_dispatch_registers_surface(self):
        from paddle_tpu.observability import compilestats
        from paddle_tpu.nn.functional.attention import _flash_fwd_lse
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 128, 2, 32).astype("f4"))
        o, lse = _flash_fwd_lse(q, q, q, None, causal=True,
                                interpret=True)
        assert o.shape == (1, 128, 2, 32)
        assert kreg.FLASH_FWD_LSE_SURFACE in compilestats.surfaces()
        st = compilestats.snapshot()[kreg.FLASH_FWD_LSE_SURFACE]
        assert st["compiles"] >= 1

    def test_traced_dispatch_inlines_into_caller(self):
        from paddle_tpu.observability import compilestats
        from paddle_tpu.nn.functional.attention import _flash_fwd_lse
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 128, 2, 32).astype("f4"))
        _flash_fwd_lse(q, q, q, None, causal=True, interpret=True)
        st0 = compilestats.snapshot()[kreg.FLASH_FWD_LSE_SURFACE]

        @jax.jit
        def outer(qv):
            o, _ = _flash_fwd_lse(qv, qv, qv, None, causal=True,
                                  interpret=True)
            return o
        outer(q)   # tracer operands: must NOT add kernel-surface rows
        st1 = compilestats.snapshot()[kreg.FLASH_FWD_LSE_SURFACE]
        assert st1["compiles"] == st0["compiles"]


# ---------------------------------------------------------------------------
# dispatch ladder: padding, masks, fallbacks
# ---------------------------------------------------------------------------

class TestDispatch:
    def _qkv(self, B=2, S=300, H=2, D=64, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda: paddle.to_tensor(rng.randn(B, S, H, D).astype("f4"))
        return mk(), mk(), mk()

    def test_padded_causal_parity(self, monkeypatch):
        q, k, v = self._qkv(S=300)   # not a multiple of 256 or 512
        ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value),
                                   atol=2e-3, rtol=2e-3)

    def test_padded_noncausal_parity(self, monkeypatch):
        q, k, v = self._qkv(S=300)
        ref = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        monkeypatch.setenv("PADDLE_TPU_ATTN_IMPL", "flash")
        out = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value),
                                   atol=2e-3, rtol=2e-3)

    def test_key_bias_mask_parity(self, monkeypatch):
        B, S = 2, 300
        q, k, v = self._qkv(B=B, S=S)
        mnp = np.zeros((B, 1, 1, S), "f4")
        mnp[:, :, :, 280:] = -1e30          # key-padding tail
        m = paddle.to_tensor(mnp)
        ref = F.scaled_dot_product_attention(q, k, v, attn_mask=m)
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        monkeypatch.setenv("PADDLE_TPU_ATTN_IMPL", "flash")
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=m)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value),
                                   atol=2e-3, rtol=2e-3)

    def test_per_query_mask_falls_back(self, monkeypatch):
        from paddle_tpu.nn.functional.attention import _select_flash
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        monkeypatch.setenv("PADDLE_TPU_ATTN_IMPL", "flash")
        reg = paddle.observability.get_registry()
        m0 = reg.get("pt_kernel_fallbacks_total")
        base = m0.value(kernel="attention", reason="mask") if m0 else 0
        sel = _select_flash(512, 512, 64, causal=False, has_mask=True,
                            mask_is_keybias=False, scale=None)
        assert not sel.use
        m = reg.get("pt_kernel_fallbacks_total")
        assert m.value(kernel="attention", reason="mask") == base + 1

    def test_constraint_ladder_reasons(self, monkeypatch):
        from paddle_tpu.nn.functional.attention import _select_flash
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        reg = paddle.observability.get_registry()

        def reason_of(**kw):
            args = dict(S=2048, Sk=2048, D=64, causal=True,
                        has_mask=False, mask_is_keybias=False,
                        scale=None)
            args.update(kw)
            return _select_flash(**args)

        assert reason_of().use                        # baseline accepts
        assert not reason_of(dropout_p=0.1).use       # dropout
        assert not reason_of(scale=0.5).use           # non-default scale
        assert not reason_of(Sk=1024).use             # cross-seq
        # masked shape past the head-folded VMEM cap
        assert not reason_of(has_mask=True, mask_is_keybias=True).use
        m = reg.get("pt_kernel_fallbacks_total")
        for r in ("dropout", "scale", "cross-seq", "mask-large"):
            assert m.value(kernel="attention", reason=r) >= 1, r

    def test_short_seq_floor_auto_vs_forced(self, monkeypatch):
        from paddle_tpu.nn.functional.attention import _select_flash
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        auto = _select_flash(256, 256, 64, causal=True, has_mask=False,
                             mask_is_keybias=False, scale=None)
        assert not auto.use                       # S < 1024, not forced
        monkeypatch.setenv("PADDLE_TPU_ATTN_IMPL", "flash")
        forced = _select_flash(256, 256, 64, causal=True, has_mask=False,
                               mask_is_keybias=False, scale=None)
        assert forced.use and forced.interpret


# ---------------------------------------------------------------------------
# fused xent: row padding + registry
# ---------------------------------------------------------------------------

class TestXentDispatch:
    def test_unaligned_rows_pad_through_kernel(self):
        from paddle_tpu.ops.pallas import fused_xent as fx
        rng = np.random.RandomState(0)
        T, V = 200, 384                       # T % 256 != 0: pads rows
        lg = jnp.asarray(rng.randn(T, V).astype("f4"))
        lb_np = rng.randint(-1, V, (T,)).astype("i4")
        lb = jnp.asarray(lb_np)
        fx._FORCE_INTERPRET = True
        try:
            out = fx.fused_softmax_xent(lg, lb)
            g = jax.grad(lambda x: jnp.sum(fx.fused_softmax_xent(x, lb))
                         )(lg)
        finally:
            fx._FORCE_INTERPRET = False
        ref = fx._ref_rowloss(lg, lb)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        gr = jax.grad(lambda x: jnp.sum(fx._ref_rowloss(x, lb)))(lg)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   rtol=1e-4, atol=1e-6)
        assert out.shape == (T,) and g.shape == (T, V)

    def test_unaligned_vocab_books_fallback(self, monkeypatch):
        from paddle_tpu.ops.pallas import fused_xent as fx
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        reg = paddle.observability.get_registry()
        m0 = reg.get("pt_kernel_fallbacks_total")
        base = m0.value(kernel="xent", reason="unaligned-vocab") \
            if m0 else 0
        rng = np.random.RandomState(0)
        lg = jnp.asarray(rng.randn(64, 100).astype("f4"))   # V % 128 != 0
        lb = jnp.asarray(rng.randint(0, 100, (64,)).astype("i4"))
        out = fx.fused_softmax_xent(lg, lb)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(fx._ref_rowloss(lg, lb)),
                                   rtol=1e-5, atol=1e-5)
        m = reg.get("pt_kernel_fallbacks_total")
        assert m.value(kernel="xent", reason="unaligned-vocab") == base + 1


# ---------------------------------------------------------------------------
# train-step gradient parity (the ISSUE 15 acceptance contract)
# ---------------------------------------------------------------------------

_FLASH_ENV = {"PADDLE_TPU_KERNEL_INTERPRET": "1",
              "PADDLE_TPU_ATTN_IMPL": "flash"}


def _grad_rel_max(ga, gb):
    worst = 0.0
    for a, b in zip(ga, gb):
        denom = float(jnp.abs(b).max()) + 1e-9
        worst = max(worst, float(jnp.abs(a - b).max()) / denom)
    return worst


def _model_grads(build, loss_of):
    """(loss, grads, params) of one train-step-equivalent fwd+bwd: the
    same value_and_grad-over-the-network shape the hapi stepper jits."""
    from paddle_tpu.framework import autograd as _ag
    from paddle_tpu.framework.random import rng_scope
    paddle.seed(0)
    net = build()
    params = [p for _, p in net.named_parameters()]
    pvals = [p._value for p in params]

    def loss_fn(pv):
        olds = [p._value for p in params]
        for p, v in zip(params, pv):
            p._value = v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                return loss_of(net)
        finally:
            for p, v in zip(params, olds):
                p._value = v

    loss, grads = jax.value_and_grad(loss_fn)(pvals)
    return float(loss), grads


class TestTrainStepParity:
    """Dense-vs-flash gradient parity in interpret mode.  Contract:
    loss within 1e-4 absolute, per-tensor gradients within 5e-3
    relative-max (fp32; docs/kernels.md "Numerics")."""

    def test_gpt_causal_hapi_train_step(self, monkeypatch):
        """Full hapi stepper fidelity: one SGD train_batch, dense vs
        flash+fused-xent — the applied update IS -lr * grad."""
        from paddle_tpu.models.gpt import (GPTConfig, GPTForPretraining,
                                           GPTPretrainingCriterion)
        import paddle_tpu.nn as nn

        cfg = GPTConfig(vocab_size=512, hidden_size=64,
                        num_hidden_layers=2, num_attention_heads=2,
                        max_position_embeddings=256)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 512, (2, 256)).astype("int32")

        def one_step():
            paddle.seed(0)
            net = GPTForPretraining(cfg)
            before = [np.asarray(p._value)
                      for _, p in net.named_parameters()]
            opt = paddle.optimizer.SGD(
                learning_rate=0.1, parameters=net.parameters())
            model = paddle.Model(net)
            model.prepare(opt, GPTPretrainingCriterion())
            loss = model.train_batch([ids], [ids])
            after = [np.asarray(p._value)
                     for _, p in net.named_parameters()]
            deltas = [a - b for a, b in zip(after, before)]
            val = loss[0] if isinstance(loss, (list, tuple)) else loss
            return float(np.asarray(val).reshape(-1)[0]), deltas

        loss_d, delta_d = one_step()
        for k, v in _FLASH_ENV.items():
            monkeypatch.setenv(k, v)
        loss_f, delta_f = one_step()
        assert abs(loss_d - loss_f) < 1e-4, (loss_d, loss_f)
        rel = _grad_rel_max([jnp.asarray(d) for d in delta_f],
                            [jnp.asarray(d) for d in delta_d])
        assert rel < 5e-3, rel

    def test_llama_rope_gqa_grads(self, monkeypatch):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, intermediate_size=256,
                          max_position_embeddings=256)
        rng = np.random.RandomState(1)
        ids = paddle.to_tensor(
            rng.randint(0, 512, (2, 256)).astype("int32"))
        lb = rng.randint(0, 512, (2, 256)).astype("int32")

        def loss_of(net):
            logits = net(ids)
            V = logits.shape[-1]
            from paddle_tpu.tensor.manipulation import reshape
            return F.cross_entropy(reshape(logits, [-1, V]),
                                   paddle.to_tensor(lb.reshape(-1)))._value

        build = lambda: LlamaForCausalLM(cfg)
        loss_d, gd = _model_grads(build, loss_of)
        for k, v in _FLASH_ENV.items():
            monkeypatch.setenv(k, v)
        loss_f, gf = _model_grads(build, loss_of)
        assert abs(loss_d - loss_f) < 1e-4
        assert _grad_rel_max(gf, gd) < 5e-3

    def test_bert_additive_mask_grads(self, monkeypatch):
        from paddle_tpu.models.bert import bert_tiny, BertForPretraining
        cfg = bert_tiny(hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        rng = np.random.RandomState(2)
        B, S = 2, 128
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, S)).astype("int32"))
        # ragged key-padding: the (B, S) 1/0 mask the model folds into
        # an additive (B, 1, 1, S) bias — the flash key-bias path
        mask_np = np.ones((B, S), "f4")
        mask_np[0, 100:] = 0.0
        mask_np[1, 64:] = 0.0
        mask = paddle.to_tensor(mask_np)
        lb = rng.randint(0, cfg.vocab_size, (B, S)).astype("int32")

        def loss_of(net):
            logits, _nsp = net(ids, attention_mask=mask)
            V = logits.shape[-1]
            from paddle_tpu.tensor.manipulation import reshape
            return F.cross_entropy(reshape(logits, [-1, V]),
                                   paddle.to_tensor(lb.reshape(-1)))._value

        build = lambda: BertForPretraining(cfg)
        loss_d, gd = _model_grads(build, loss_of)
        for k, v in _FLASH_ENV.items():
            monkeypatch.setenv(k, v)
        loss_f, gf = _model_grads(build, loss_of)
        assert abs(loss_d - loss_f) < 1e-4
        assert _grad_rel_max(gf, gd) < 5e-3
