"""Paged KV-cache subsystem (inference/kvcache.py + the paged mode of
inference/serving.py): bitwise parity paged == dense == generate(),
prefix-cache hit == cold prefill, page-pressure eviction/re-admission,
the int8 KV accuracy contract, allocator free-list invariants, and the
donation regression (live device bytes flat across chunks).

The parity tests are the subsystem's core claim: the paged gather
materializes exactly the values the dense path holds, then runs the
identical compiled math — so greedy decode through pages must reproduce
the dense engine and generate() token for token, bit for bit.
"""
import gc

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework import guardian
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.inference.kvcache import (PagedKVManager, quantize_kv,
                                          dequantize_kv)
from paddle_tpu.models import (GPTForPretraining, LlamaForCausalLM,
                               gpt3_tiny, llama_tiny)

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    return GPTForPretraining(gpt3_tiny())


@pytest.fixture(scope="module")
def llama():
    paddle.seed(0)
    net = LlamaForCausalLM(llama_tiny())
    rng = np.random.RandomState(3)
    for _, p in net.named_parameters():
        if len(p.shape) >= 2:
            p._value = jnp.asarray(
                rng.normal(0, 0.05, tuple(p.shape)).astype("float32"))
    return net


def _gen(net, prompt_np, n):
    if prompt_np.ndim == 1:
        prompt_np = prompt_np[None, :]
    ids, _ = net.generate(paddle.to_tensor(prompt_np), max_new_tokens=n)
    return np.asarray(ids._value)


def _run_all(eng, prompts, budgets):
    reqs = [eng.submit(p, int(b)) for p, b in zip(prompts, budgets)]
    eng.run()
    return reqs


class TestPagedParity:
    def test_paged_bitwise_matches_dense_and_generate(self, gpt):
        """Acceptance: mixed ragged prompts/budgets — paged engine ==
        dense engine == generate(), token for token; and the paged
        pool's resident high-water stays below dense's S x MAX
        allocation (HBM scales with live tokens)."""
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, 1024, (n,)).astype("int32")
                   for n in (5, 11, 8, 3)]
        budgets = [6, 3, 8, 5]
        dense = ServingEngine(gpt, num_slots=2, chunk=4,
                              prefill_buckets=(8, 16))
        dn = _run_all(dense, prompts, budgets)
        paged = ServingEngine(gpt, num_slots=2, chunk=4,
                              prefill_buckets=(8, 16), kv_mode="paged",
                              page_size=8)
        pg = _run_all(paged, prompts, budgets)
        for p, b, d, q in zip(prompts, budgets, dn, pg):
            want = _gen(gpt, p, b)[0][:len(d.tokens)]
            np.testing.assert_array_equal(
                np.asarray(d.tokens, np.int32), want)
            assert q.tokens == d.tokens
        paged._kv.check()
        dense_bytes = sum(2 * k.nbytes for k, _ in dense._caches)
        hw = paged._kv.stats["resident_high_water_bytes"]
        assert 0 < hw < dense_bytes
        # live-token scaling: the trace never holds more than 2 slots x
        # (11 + 8 = 19 tokens -> 3 pages) + first-chunk headroom, far
        # under the 2 x (128/8 = 16) pages dense reserves implicitly
        assert hw <= 8 * paged._kv.page_bytes
        # the same accounting flows through the pt_kvcache_* gauges;
        # after the run only prefix-cache entries keep pages resident
        # (slots all released), still far under dense's S x MAX
        import paddle_tpu.observability as obs
        reg = obs.get_registry()
        g = reg.get("pt_kvcache_resident_kv_bytes")
        assert g is not None and g.value() == paged._kv.resident_bytes
        assert reg.get("pt_kvcache_pages_in_use").value() == \
            paged._kv.pages_in_use
        assert g.value() < dense_bytes

    def test_llama_paged_parity(self, llama):
        """The paged gather/scatter rides gpt._cached_attention, which
        LLaMA (rope + GQA) and GPT-MoE share — prove the non-GPT wiring
        with the family whose attention differs most."""
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 512, (n,)).astype("int32")
                   for n in (5, 9)]
        eng = ServingEngine(llama, num_slots=2, chunk=4,
                            prefill_buckets=(16,), kv_mode="paged",
                            page_size=8)
        reqs = _run_all(eng, prompts, [7, 4])
        for p, b, r in zip(prompts, [7, 4], reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(llama, p, b)[0])
        eng._kv.check()

    def test_gpt_moe_paged_parity(self):
        """Third family: GPT-MoE decode routes per-token through the
        shared _cached_block, so pages must carry MoE serving too
        (capacity lifted so routing never drops — the same causal-
        consistency caveat as test_generation's MoE parity)."""
        from paddle_tpu.models import GPTMoEForPretraining, gpt_moe_tiny
        paddle.seed(0)
        cfg = gpt_moe_tiny(num_hidden_layers=2)
        moe = GPTMoEForPretraining(cfg)
        for m in moe.gpt.moe_layers():
            m.gate.capacity_factor = float(cfg.num_experts * cfg.top_k)
        rng = np.random.RandomState(3)
        p = rng.randint(0, 1024, (6,)).astype("int32")
        eng = ServingEngine(moe, num_slots=1, chunk=4,
                            prefill_buckets=(8,), kv_mode="paged",
                            page_size=8)
        (r,) = _run_all(eng, [p], [5])
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      _gen(moe, p, 5)[0])
        eng._kv.check()

    def test_dense_mode_rejects_paged_knobs(self, gpt):
        with pytest.raises(ValueError, match="kv_mode='paged'"):
            ServingEngine(gpt, kv_mode="dense", kv_dtype="int8")
        with pytest.raises(ValueError, match="kv_mode"):
            ServingEngine(gpt, kv_mode="blocked")


class TestPrefixCache:
    def test_hit_bitwise_equals_cold_prefill(self, gpt):
        """Requests sharing a system prompt map its cached pages and
        prefill only their suffix — output must be bitwise-identical to
        each request's own cold generate() run, with exactly one cold
        prefill of the shared prefix (acceptance: shared prompts
        prefill once)."""
        rng = np.random.RandomState(11)
        sysp = rng.randint(0, 1024, (16,)).astype("int32")
        prompts = [np.concatenate(
            [sysp, rng.randint(0, 1024, (4,)).astype("int32")])
            for _ in range(3)]
        eng = ServingEngine(gpt, num_slots=3, chunk=4,
                            prefill_buckets=(8, 32), kv_mode="paged",
                            page_size=8)
        reqs = _run_all(eng, prompts, [5, 5, 5])
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, 5)[0])
        st = eng._kv.stats
        assert st["prefix_misses"] == 1          # only the first is cold
        assert st["prefix_hits"] == 2
        # both hits skipped the full page-aligned prefix (16 tokens)
        assert st["prefix_saved_tokens"] == 32
        eng._kv.check()
        evs = guardian.events("serving_prefix_hit")
        assert len(evs) >= 2 and evs[-1]["cached_tokens"] == 16
        import paddle_tpu.observability as obs
        reg = obs.get_registry()
        assert reg.get("pt_kvcache_prefix_hits_total").value() >= 2
        assert reg.get(
            "pt_kvcache_prefix_saved_tokens_total").value() >= 32

    def test_hit_across_runs(self, gpt):
        """The prefix registered by one run() serves later runs — the
        system-prompt-reuse pattern the cache exists for."""
        rng = np.random.RandomState(12)
        p = rng.randint(0, 1024, (24,)).astype("int32")
        eng = ServingEngine(gpt, num_slots=1, chunk=4,
                            prefill_buckets=(32,), kv_mode="paged",
                            page_size=8)
        (r1,) = _run_all(eng, [p], [4])
        assert eng._kv.stats["prefix_hits"] == 0
        (r2,) = _run_all(eng, [p], [4])      # same prompt, warm cache
        assert eng._kv.stats["prefix_hits"] == 1
        assert r2.tokens == r1.tokens
        eng._kv.check()

    def test_disabled_prefix_cache_still_bitwise(self, gpt):
        rng = np.random.RandomState(13)
        sysp = rng.randint(0, 1024, (16,)).astype("int32")
        prompts = [np.concatenate(
            [sysp, rng.randint(0, 1024, (3,)).astype("int32")])
            for _ in range(2)]
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(32,), kv_mode="paged",
                            page_size=8, prefix_cache=False)
        reqs = _run_all(eng, prompts, [4, 4])
        assert eng._kv.stats["prefix_hits"] == 0
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, 4)[0])


class TestPagePressure:
    def test_eviction_and_readmission_completes_all(self, gpt):
        """A pool too small for both in-flight requests: the younger is
        preempted mid-decode (pages freed, requeued) and resumes by
        recompute after the older finishes — every request still
        completes bitwise-identical to its solo generate() run."""
        rng = np.random.RandomState(21)
        prompts = [rng.randint(0, 1024, (6,)).astype("int32")
                   for _ in range(2)]
        budgets = [26, 26]                   # 32 tokens = 4 pages each
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8, 16, 32, 64),
                            kv_mode="paged", page_size=8, num_pages=7,
                            prefix_cache=False)   # 6 usable < 2 x 4
        reqs = _run_all(eng, prompts, budgets)
        assert eng.stats["page_evictions"] >= 1
        assert sum(r.evictions for r in reqs) >= 1
        for p, b, r in zip(prompts, budgets, reqs):
            assert r.finish_reason is not None
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32),
                _gen(gpt, p, b)[0][:len(r.tokens)])
            assert len(r.tokens) == b
        eng._kv.check()
        assert eng._kv.pages_in_use == 0     # all released at finish
        evs = guardian.events("serving_page_evict")
        assert evs and evs[-1]["pages_freed"] > 0

    def test_admission_blocks_fcfs_head_of_line(self, gpt):
        """When the queue head cannot reserve pages, admission STOPS —
        a smaller later request must not skip ahead (deliberate FCFS
        head-of-line blocking, same as the dense engine's slot gate)."""
        rng = np.random.RandomState(22)
        big = rng.randint(0, 1024, (30,)).astype("int32")
        big2 = rng.randint(0, 1024, (30,)).astype("int32")
        small = rng.randint(0, 1024, (4,)).astype("int32")
        eng = ServingEngine(gpt, num_slots=3, chunk=4,
                            prefill_buckets=(8, 16, 32), kv_mode="paged",
                            page_size=8, num_pages=9,
                            prefix_cache=False)   # 8 usable pages
        a = eng.submit(big, 8)               # 34-token coverage: 5 pages
        b = eng.submit(big2, 8)              # 5 pages > 3 free: blocked
        c = eng.submit(small, 4)             # 1 page — could sneak in
        eng.step()
        assert a.slot is not None
        assert b.slot is None and c.slot is None    # no skip-ahead
        while eng.scheduler.has_work:
            eng.step()
        for r, p, n in ((a, big, 8), (b, big2, 8), (c, small, 4)):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32),
                _gen(gpt, p, n)[0][:len(r.tokens)])
        # FCFS preserved: b admitted before c
        assert b.admit_ns <= c.admit_ns
        eng._kv.check()

    def test_unresumable_requests_reserve_full_extent(self, gpt):
        """Regression: two requests that could each outgrow the largest
        prefill bucket (so eviction would strand them) must NOT be
        over-admitted on first-chunk reservations and then hard-fail
        the run when the pool dries up mid-decode — the second waits at
        admission instead, and both complete."""
        rng = np.random.RandomState(24)
        prompts = [rng.randint(0, 1024, (8,)).astype("int32")
                   for _ in range(2)]
        # prompt 8 + budget 32 = 40 > buckets[-1] = 16 -> unresumable,
        # full extent = 5 pages each; pool of 8 can only hold one
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(16,), kv_mode="paged",
                            page_size=8, num_pages=9,
                            prefix_cache=False)
        reqs = _run_all(eng, prompts, [32, 32])
        assert eng.stats["page_evictions"] == 0     # serialized, not torn
        assert eng.stats["max_concurrent"] == 1
        for p, r in zip(prompts, reqs):
            assert len(r.tokens) == 32
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, 32)[0])
        eng._kv.check()

    def test_pool_too_small_rejected_at_submit(self, gpt):
        """A request the pool can never finish even running alone is a
        sizing error caught at submit() — BEFORE it can decode for
        hundreds of tokens, evict everything else, and only then
        discover it cannot proceed."""
        rng = np.random.RandomState(23)
        eng = ServingEngine(gpt, num_slots=1, chunk=4,
                            prefill_buckets=(32,), kv_mode="paged",
                            page_size=8, num_pages=3)  # 2 usable pages
        with pytest.raises(ValueError, match="KV pages at full decode"):
            eng.submit(rng.randint(0, 1024, (20,)).astype("int32"), 8)
        # a request that DOES fit the pool end-to-end is served
        r = eng.submit(rng.randint(0, 1024, (6,)).astype("int32"), 8)
        eng.run()
        assert r.finish_reason is not None and len(r.tokens) == 8


class TestInt8KV:
    def test_roundtrip_error_bound(self):
        """The documented per-element contract: |dq(q(x)) - x| <=
        scale/2 with one absmax scale per token row."""
        rng = np.random.RandomState(31)
        x = jnp.asarray(rng.normal(0, 2, (4, 6, 8)).astype("float32"))
        q, s = quantize_kv(x)
        assert q.dtype == jnp.int8 and s.shape == (4,)
        err = jnp.abs(dequantize_kv(q, s, x.dtype) - x)
        assert float(jnp.max(err - s[..., None, None] / 2)) <= 1e-6
        # zero rows roundtrip to exactly zero (the trash-page case)
        z = jnp.zeros((2, 3, 4), jnp.float32)
        qz, sz = quantize_kv(z)
        assert float(jnp.max(jnp.abs(
            dequantize_kv(qz, sz, z.dtype)))) == 0.0

    def test_logit_drift_within_documented_tolerance(self, gpt):
        """docs/serving.md pins relative max-logit-drift <= 3e-2 on
        tiny-GPT when a decode step re-reads int8-roundtripped KV
        (measured ~1.3e-3 — the bound carries margin, like grad_comm's
        quantized-reduce contract)."""
        from paddle_tpu.models.generation import build_apply
        cfg = gpt3_tiny()
        params = [p for _, p in gpt.named_parameters()]
        pv = [p._value for p in params]
        apply = build_apply(gpt, params)
        rng = np.random.RandomState(32)
        n, MAX = 24, 32
        nH = cfg.num_attention_heads
        D = cfg.hidden_size // nH
        ids = rng.randint(0, 1024, (2, n)).astype("int32")
        caches = [(jnp.zeros((2, MAX, nH, D)), jnp.zeros((2, MAX, nH, D)))
                  for _ in range(cfg.num_hidden_layers)]
        logits, caches = apply(pv, jnp.asarray(ids), caches,
                               jnp.asarray(0))
        nxt = jnp.argmax(logits[:, n - 1], -1).astype(jnp.int32)
        exact, _ = apply(pv, nxt[:, None], caches, jnp.asarray(n))
        rt = [(dequantize_kv(*quantize_kv(k), k.dtype),
               dequantize_kv(*quantize_kv(v), v.dtype))
              for k, v in caches]
        drift, _ = apply(pv, nxt[:, None], rt, jnp.asarray(n))
        rel = float(jnp.max(jnp.abs(exact - drift))
                    / jnp.max(jnp.abs(exact)))
        assert rel <= 3e-2

    def test_int8_engine_completes_with_high_agreement(self, gpt):
        """End-to-end int8 serving: every request completes, prefill
        first-tokens are EXACT (quantization error only enters on pool
        re-read), and decode agrees with the dense engine on >= 95% of
        tokens on tiny-GPT."""
        rng = np.random.RandomState(33)
        prompts = [rng.randint(0, 1024, (n,)).astype("int32")
                   for n in (6, 10, 4)]
        budgets = [8, 6, 8]
        dense = ServingEngine(gpt, num_slots=2, chunk=4,
                              prefill_buckets=(8, 16))
        dn = _run_all(dense, prompts, budgets)
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8, 16), kv_mode="paged",
                            page_size=8, kv_dtype="int8")
        reqs = _run_all(eng, prompts, budgets)
        agree = total = 0
        for d, q in zip(dn, reqs):
            assert q.finish_reason is not None
            assert q.tokens[0] == d.tokens[0]    # exact prefill pick
            m = min(len(d.tokens), len(q.tokens))
            agree += sum(int(a == b) for a, b
                         in zip(d.tokens[:m], q.tokens[:m]))
            total += m
        assert agree / total >= 0.95
        eng._kv.check()
        # int8 pool pages are ~4x smaller than fp32 (scale planes aside)
        assert eng._kv.page_bytes < dense._caches[0][0].dtype.itemsize \
            * eng._kv.page_size * sum(2 * nh * d
                                      for nh, d in eng._kv.spec) / 2


class TestAllocator:
    SPEC = [(2, 4), (2, 4)]

    def _mgr(self, **kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("page_size", 8)
        kw.setdefault("num_pages", 9)
        kw.setdefault("cache_dtype", jnp.float32)
        return PagedKVManager(self.SPEC, **kw)

    def test_lifecycle_invariants(self):
        """plan/bind/ensure/release churn with check() after every
        transition: refcounts == holders, free list exact complement,
        trash page never allocated."""
        kv = self._mgr()
        rng = np.random.RandomState(41)
        pr = [rng.randint(0, 99, (12,)).astype(np.int32)
              for _ in range(2)]
        # NB: a plan holds page references until bind/abandon, so
        # check() (which counts slot+prefix holders only) is valid at
        # bind boundaries, not between plan and bind
        for s, p in enumerate(pr):
            pl = kv.plan(p, budget=8, chunk=4)
            assert pl is not None
            kv.bind(s, pl)
            kv.check()
        assert kv.ensure(0, 2) and kv.check()
        assert kv.release(0) > 0
        kv.check()
        kv.release(1)
        kv.check()
        # prefix entries may still hold pages; a reset drops everything
        kv.reset()
        kv.check()
        assert kv.pages_in_use == 0

    def test_alloc_all_or_nothing(self):
        kv = self._mgr(num_pages=4, prefix_cache=False)  # 3 usable
        p = np.arange(12, dtype=np.int32)
        pl = kv.plan(p, budget=8, chunk=4)               # needs 2
        kv.bind(0, pl)
        # 1 free page left; a 2-page plan must fail WITHOUT leaking it
        assert kv.plan(p, budget=8, chunk=4) is None
        assert len(kv._free) == 1
        kv.check()
        kv.release(0)
        kv.check()

    def test_prefix_lru_reclaim_under_pressure(self):
        """Cached prefixes are best-effort: allocation pressure reclaims
        them LRU-first, and pages still mapped by a slot survive the
        entry drop."""
        kv = self._mgr(num_pages=6)                       # 5 usable
        a = np.arange(8, dtype=np.int32)
        b = np.arange(8, 16, dtype=np.int32)
        kv.bind(0, kv.plan(a, budget=2, chunk=2))   # 1 page + prefix ref
        kv.release(0)                                # prefix entry holds it
        assert kv.pages_in_use == 1 and len(kv._prefix) == 1
        kv.bind(0, kv.plan(b, budget=2, chunk=2))
        kv.release(0)
        assert len(kv._prefix) == 2
        kv.check()
        # demand 4 of the 3 free pages: exactly ONE entry is reclaimed,
        # and it is the least-recently-used (a's, the older bind)
        big = np.arange(100, 132, dtype=np.int32)
        pl = kv.plan(big, budget=8, chunk=8)
        assert pl is not None and len(kv._prefix) == 1
        # b's entry survived (entries are digest-keyed; identify by the
        # stored prefix tokens backing the full-content hit check)
        assert any(np.array_equal(toks, b[:8])
                   for _, toks in kv._prefix.values())
        kv.abandon(pl)
        kv.check()

    def test_plan_hits_existing_prefix(self):
        kv = self._mgr()
        p = np.arange(20, dtype=np.int32)
        kv.bind(0, kv.plan(p, budget=4, chunk=4))
        in_use = kv.pages_in_use
        # same prompt again: the page-aligned prefix (16 tokens = 2
        # pages) is shared, only suffix+chunk pages are fresh
        pl = kv.plan(p, budget=4, chunk=4)
        assert pl["k"] == 16
        assert pl["pages"][:2] == [int(kv.table[0][0]),
                                   int(kv.table[0][1])]
        kv.bind(1, pl)
        assert kv.pages_in_use == in_use + 1     # one fresh page only
        kv.check()

    def test_plan_survives_reclaim_of_its_own_hit_entry(self):
        """Regression x2: (a) plan() must hold the hit prefix entry's
        pages BEFORE allocating, so reclaim can never recycle them as
        'fresh' (one physical page mapped at two logical positions);
        (b) an allocation that cannot succeed even by draining the
        whole prefix cache must fail WITHOUT draining it."""
        kv = self._mgr(num_pages=4)                       # 3 usable
        a = np.arange(24, dtype=np.int32)
        kv.bind(0, kv.plan(a[:16], budget=2, chunk=2))    # 3 pages
        kv.release(0)             # prefix entries (8- and 16-tok) hold 2
        assert kv.pages_in_use == 2
        # hits the 16-token prefix but needs 2 fresh pages with 1 free:
        # the plan's own holds make the hit pages unreclaimable, so the
        # request is unservable — and the cache survives the failure
        pl = kv.plan(a, budget=2, chunk=2)
        assert pl is None
        assert kv.pages_in_use == 2 and len(kv._prefix) == 2
        kv.check()

    def test_refresh_weights_drops_stale_prefix(self):
        """Regression: refresh_weights() must clear the prefix cache —
        cached-prefix KV computed with the OLD weights served to a new
        admission would silently break parity with generate()."""
        paddle.seed(0)
        gpt = GPTForPretraining(gpt3_tiny())
        rng = np.random.RandomState(43)
        p = rng.randint(0, 1024, (16,)).astype("int32")
        eng = ServingEngine(gpt, num_slots=1, chunk=4,
                            prefill_buckets=(16,), kv_mode="paged",
                            page_size=8)
        _run_all(eng, [p], [4])          # registers p's prefix pages
        for _, w in gpt.named_parameters():
            if len(w.shape) >= 2:
                w._value = w._value * 1.01
        eng.refresh_weights()
        assert len(eng._kv._prefix) == 0 and eng._kv.pages_in_use == 0
        (r,) = _run_all(eng, [p], [4])   # must MISS and re-prefill
        assert eng._kv.stats["prefix_hits"] == 0
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      _gen(gpt, p, 4)[0])
        eng._kv.check()

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="divide"):
            self._mgr(page_size=7)
        with pytest.raises(ValueError, match="num_pages"):
            self._mgr(num_pages=1)
        with pytest.raises(ValueError, match="kv_dtype"):
            self._mgr(kv_dtype="int4")


class TestDonation:
    def test_live_device_bytes_flat_across_chunks(self, gpt):
        """The donation regression: the paged decode/prefill jits donate
        slot state + pools, so steady-state decode must not accumulate
        live device buffers chunk over chunk."""
        rng = np.random.RandomState(51)
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8,), kv_mode="paged",
                            page_size=8)
        eng.submit(rng.randint(0, 1024, (6,)).astype("int32"), 40)
        eng.step()                       # admit + first chunk
        def live():
            gc.collect()
            return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in jax.live_arrays())
        base = live()
        sizes = []
        for _ in range(4):
            old_pool = eng._pools[0][0]   # K pool of layer 0, pre-chunk
            eng.step()
            # the decode jit donates the pools: the pre-chunk buffer
            # must be INVALIDATED, not kept as a double buffer
            with pytest.raises(RuntimeError, match="[Dd]onat|deleted"):
                _ = old_pool + 0
            sizes.append(live())
        assert max(sizes) <= base, \
            f"live device bytes grew across chunks: {base} -> {sizes}"
        while eng.scheduler.has_work:
            eng.step()

    def test_dense_engine_also_flat(self, gpt):
        rng = np.random.RandomState(52)
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8,))
        eng.submit(rng.randint(0, 1024, (6,)).astype("int32"), 40)
        eng.step()
        def live():
            gc.collect()
            return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in jax.live_arrays())
        base = live()
        sizes = []
        for _ in range(4):
            eng.step()
            sizes.append(live())
        assert max(sizes) <= base
        while eng.scheduler.has_work:
            eng.step()


class TestPrefixKeyDigests:
    """PR 8 satellite: prefix-cache keys are CHAINED per-page digests —
    admission-time key construction is one O(n) pass over the prompt
    (the old whole-prefix raw-byte keys were quadratic), and a digest
    collision degrades to a miss via the full-content hit check."""

    SPEC = [(2, 4)] * 2

    def _mgr(self, **kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 512)
        kw.setdefault("page_size", 8)
        kw.setdefault("num_pages", 200)
        kw.setdefault("cache_dtype", jnp.float32)
        return PagedKVManager(self.SPEC, **kw)

    def test_key_construction_linear_in_prompt(self):
        """The machine-checked regression: bytes hashed per plan() ==
        the prompt's page-aligned bytes (one pass), NOT the quadratic
        sum over every prefix length the old scheme paid."""
        kv = self._mgr()
        n = 504                                   # 63 pages
        p = np.arange(n, dtype=np.int32)
        kv.stats["prefix_key_bytes_hashed"] = 0
        pl = kv.plan(p, budget=4, chunk=4)
        one_pass = (n // kv.page_size) * kv.page_size * 4
        assert kv.stats["prefix_key_bytes_hashed"] == one_pass
        kv.bind(0, pl)
        # the hit path pays one more pass, never pages^2/2
        kv.stats["prefix_key_bytes_hashed"] = 0
        pl2 = kv.plan(p, budget=4, chunk=4)
        assert kv.stats["prefix_key_bytes_hashed"] == one_pass
        assert pl2["k"] == (n // kv.page_size) * kv.page_size - \
            kv.page_size * 0 - (0 if n % kv.page_size else kv.page_size)
        kv.abandon(pl2)
        kv.release(0)
        kv.check()

    def test_long_prompt_hit_still_bitwise_shares(self, gpt):
        """End-to-end long-prompt regression: a shared long prefix hits
        (suffix-only prefill) and the output matches cold generate()."""
        rng = np.random.RandomState(60)
        base = rng.randint(0, 1024, (96,)).astype("int32")
        prompts = [np.concatenate([base,
                                   rng.randint(0, 1024, (4,))
                                   .astype("int32")])
                   for _ in range(2)]
        eng = ServingEngine(gpt, num_slots=2, chunk=4, max_seq_len=128,
                            prefill_buckets=(8, 16, 32, 64, 100),
                            kv_mode="paged", page_size=8)
        reqs = [eng.submit(p, 6) for p in prompts]
        eng.run()
        assert eng._kv.stats["prefix_hits"] >= 1
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, 6)[0])
        eng._kv.check()

    def test_digest_collision_degrades_to_miss(self, monkeypatch):
        """Force every digest to collide: the stored-token equality
        check must reject the bogus hit (a miss, never wrong sharing)."""
        kv = self._mgr()
        monkeypatch.setattr(
            type(kv), "_page_keys",
            lambda self, prompt: [b"same"] * (len(prompt)
                                              // self.page_size))
        a = np.arange(32, dtype=np.int32)
        b = np.arange(100, 132, dtype=np.int32)    # same length, differs
        kv.bind(0, kv.plan(a, budget=4, chunk=4))
        pl = kv.plan(b, budget=4, chunk=4)
        assert pl["k"] == 0                        # collision -> miss
        kv.abandon(pl)
        kv.release(0)
        kv.check()
