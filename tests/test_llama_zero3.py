"""BASELINE config #5: LLaMA architecture under ZeRO-3 (GroupSharded
p_g_os) — sharded run == single-device golden (reference pattern:
dygraph_group_sharded_stage3.py parity tests, SURVEY.md §4)."""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _data(steps=3, B=8, S=16, V=512, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, V, (B, S)).astype("i8"),
             rng.randint(0, V, (B, S)).astype("i8")) for _ in range(steps)]


def _train(net, data, lr=1e-3):
    model = paddle.Model(net)
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    losses = []
    for x, y in data:
        res = model.train_batch([x], [y[..., None]])
        losses.append(res[0])
    return losses


def test_llama_zero3_matches_single_device():
    assert jax.device_count() == 8
    cfg = llama_tiny()
    data = _data()

    paddle.seed(11)
    golden = LlamaForCausalLM(cfg)
    golden_losses = _train(golden, data)
    assert all(np.isfinite(l) for l in golden_losses)

    paddle.seed(11)
    net = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    wrapped, opt, _ = group_sharded_parallel(net, opt, level="p_g_os")
    model = paddle.Model(wrapped)
    model.prepare(opt, nn.CrossEntropyLoss())
    losses = []
    for x, y in data:
        res = model.train_batch([x], [y[..., None]])
        losses.append(res[0])

    np.testing.assert_allclose(losses, golden_losses, rtol=3e-4, atol=3e-5)
    # ZeRO-3: large weights actually sharded across the fsdp axis
    big = [p for p in net.parameters() if len(p.shape) >= 2 and
           int(np.prod(p.shape)) >= 64 * 64]
    assert any(not p._value.sharding.is_fully_replicated for p in big), \
        "stage-3 should shard the large parameters"


def test_llama_gqa_forward_shape():
    cfg = llama_tiny()
    paddle.seed(0)
    net = LlamaForCausalLM(cfg)
    x = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
    out = net(paddle.to_tensor(x.astype("i8")))
    assert tuple(out.shape) == (2, 16, cfg.vocab_size)
