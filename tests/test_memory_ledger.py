"""HBM memory ledger (ISSUE 20 tentpole) + satellites.

Acceptance anchors:

- static side: every registry jit surface gets a row in the
  ``memory.json`` snapshot (never-compiled ones as explicit
  placeholders); the compile hook feeds the ledger; an over-envelope
  surface raises the guardian ``memory_budget`` event;
- dynamic side: the live-buffer census reconciles against the real
  ``PagedKVManager``'s analytic bookkeeping within 1% on the CPU
  proxy, forecasts OOM from a linear growth trend, and books the
  ``pt_memory_*`` gauges (``-1`` forecast sentinel included);
- chaos e2e: shrinking the page pool mid-run trips ``hbm_pressure``,
  the forensic bundle carries ``memory.jsonl``, and ``doctor`` ranks
  ``memory_pressure`` as the top cause;
- satellites: dropped-span ring-overflow accounting surfaces in the
  counter, the trace metadata and ``report --requests``; the timeline
  guardian clock offset is minted once with no capture; two
  near-simultaneous watchdog trips coalesce into ONE bundle and
  retention never deletes a mid-write dot-tmp dir; the bench gate
  requires ``telemetry/memory.json`` next to committed ``BENCH_*``.
"""
import collections
import json
import os
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu import observability as obs
from paddle_tpu.framework import failpoints, guardian
from paddle_tpu.inference.kvcache import PagedKVManager
from paddle_tpu.observability import (compilestats, doctor, export,
                                      flight, memory, metrics, report,
                                      timeline, tracing, watch)

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    flight.disable()
    obs.enable(True)
    obs.get_registry().reset()
    tracing.reset()
    compilestats.reset()
    memory.reset()
    failpoints.clear()
    guardian.clear_events()
    yield
    flight.disable()
    obs.enable(True)
    obs.get_registry().reset()
    tracing.reset()
    compilestats.reset()
    memory.reset()
    failpoints.clear()
    guardian.clear_events()


def _gauge(name, **labels):
    """Latest value of one gauge/counter series from the registry."""
    key = tuple(sorted(labels.items()))
    for rec in export.snapshot():
        if rec["metric"] == name and \
                tuple(sorted(rec["labels"].items())) == key:
            return rec["value"]
    return None


class FakePool:
    """Minimal PagedKVManager accounting surface for census tests."""

    def __init__(self, num_pages=11, page_bytes=1024, in_use=0):
        self.num_pages = num_pages
        self.page_bytes = page_bytes
        self._in_use = in_use
        self._buf = np.zeros(num_pages * page_bytes, np.int8)

    @property
    def pages_in_use(self):
        return self._in_use

    @property
    def resident_bytes(self):
        return self._in_use * self.page_bytes

    @property
    def pool_bytes(self):
        return self.num_pages * self.page_bytes

    def device_pools(self):
        return [(self._buf,)]


def _mgr(num_pages=9):
    return PagedKVManager(spec=[(2, 8)], num_slots=2, max_seq_len=16,
                         page_size=4, num_pages=num_pages,
                         cache_dtype="float32")


# -- static side -----------------------------------------------------------

class TestStaticLedger:
    def test_record_books_total_and_gauges(self, monkeypatch):
        monkeypatch.setenv(memory.HBM_ENVELOPE_ENV, "1000000")
        row = memory.record_static(
            "kernel.flash_fwd",
            {"argument": 100, "output": 60, "temp": 30,
             "generated_code": 10},
            cost={"flops": 7.0, "bytes accessed": 9.0})
        assert row["total_bytes"] == 200
        assert row["flops"] == 7.0 and row["bytes_accessed"] == 9.0
        assert _gauge("pt_memory_static_bytes",
                      surface="kernel.flash_fwd", kind="total") == 200
        assert _gauge("pt_memory_static_bytes",
                      surface="kernel.flash_fwd", kind="argument") == 100
        frac = _gauge("pt_memory_budget_frac",
                      surface="kernel.flash_fwd")
        assert frac == pytest.approx(200 / 1000000)

    def test_partial_kinds_degrade_not_crash(self):
        # XLA:CPU under-reports: absent kinds stay None, the total sums
        # only what the backend exposed
        row = memory.record_static("hapi.train_step",
                                   {"argument": 50, "output": 14})
        assert row["kinds"]["temp"] is None
        assert row["kinds"]["generated_code"] is None
        assert row["total_bytes"] == 64
        assert _gauge("pt_memory_static_bytes",
                      surface="hapi.train_step", kind="temp") is None

    def test_over_envelope_emits_memory_budget(self, monkeypatch):
        monkeypatch.setenv(memory.HBM_ENVELOPE_ENV, "1000")
        memory.record_static("generation.decode", {"argument": 4000})
        (e,) = [e for e in guardian.events()
                if e["event"] == "memory_budget"]
        assert e["surface"] == "generation.decode"
        assert e["bytes"] == 4000 and e["envelope"] == 1000
        assert e["frac"] == pytest.approx(4.0)

    def test_compile_hook_feeds_ledger(self):
        f = compilestats.wrap(jax.jit(lambda x: x * 2.0 + 1.0),
                              "kernel.flash_fwd", budget=4)
        x = jnp.ones((16, 8), jnp.float32)
        np.testing.assert_allclose(np.asarray(f(x)),
                                   np.asarray(x) * 2.0 + 1.0)
        snap = memory.static_snapshot()
        assert "kernel.flash_fwd" in snap
        row = snap["kernel.flash_fwd"]
        assert row["compiled"] is True
        # at least argument/output bytes exist even on XLA:CPU
        assert row["total_bytes"] is not None and row["total_bytes"] > 0

    def test_snapshot_covers_every_registry_surface(self):
        from paddle_tpu.analysis.allowlist import COMPILE_SURFACES
        memory.record_static("serving.decode_chunk", {"argument": 8})
        doc = memory.snapshot()
        for s in COMPILE_SURFACES:
            assert s in doc["surfaces"], s
        assert doc["surfaces"]["serving.decode_chunk"]["compiled"]
        placeholders = [s for s, r in doc["surfaces"].items()
                        if not r["compiled"]]
        assert placeholders          # never-compiled rows are explicit
        for s in placeholders:
            assert doc["surfaces"][s]["total_bytes"] is None

    def test_write_memory_json_atomic(self, tmp_path):
        memory.record_static("hapi.eval_step", {"argument": 32})
        path = memory.write_memory_json(str(tmp_path / "memory.json"))
        assert not os.path.exists(path + ".tmp")
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["hbm_envelope_bytes"] == memory.hbm_envelope()
        assert doc["surfaces"]["hapi.eval_step"]["total_bytes"] == 32
        assert "dynamic" in doc and "platform" in doc


# -- dynamic side ----------------------------------------------------------

class TestCensus:
    def test_counts_live_arrays_host_side(self):
        x = jnp.zeros((128, 64), jnp.float32)
        rec = memory.census("fit_step")
        assert rec["live_bytes"] >= x.nbytes
        assert rec["live_buffers"] >= 1
        assert rec["point"] == "fit_step"
        assert rec["kv_occupancy"] is None    # no pool registered
        del x

    def test_reconciles_with_real_paged_pool(self):
        # PagedKVManager registers itself at construction; the measured
        # device-buffer bytes must reconcile with the pool's analytic
        # bookkeeping within 1% (the ISSUE acceptance bound)
        mgr = _mgr()
        mgr._free = mgr._free[:-4]            # 4 of 8 pages in use
        rec = memory.census("serving_sync")
        assert rec["kv_pool_bytes"] == mgr.pool_bytes
        assert abs(rec["kv_device_bytes"] - rec["kv_pool_bytes"]) \
            <= 0.01 * rec["kv_pool_bytes"]
        assert rec["kv_pages_in_use"] == 4
        assert rec["kv_pages_total"] == mgr.num_pages - 1
        assert rec["kv_occupancy"] == pytest.approx(0.5)
        assert rec["kv_headroom_bytes"] == 4 * mgr.page_bytes
        assert rec["kv_resident_bytes"] == 4 * mgr.page_bytes

    def test_reset_never_double_registers(self):
        mgr = _mgr()
        mgr.reset()
        mgr.reset()                           # re-registers by identity
        rec = memory.census()
        assert rec["kv_pool_bytes"] == mgr.pool_bytes
        assert rec["kv_pages_total"] == mgr.num_pages - 1

    def test_dropped_pool_unregisters_via_weakref(self):
        pool = FakePool(in_use=5)
        memory.register_kv_pool(pool)
        assert memory.census()["kv_occupancy"] is not None
        del pool
        assert memory.census()["kv_occupancy"] is None

    def test_forecast_linear_growth_and_flat(self):
        pool = FakePool(num_pages=101, page_bytes=100, in_use=10)
        memory.register_kv_pool(pool)
        for _ in range(6):                    # +5 pages per census
            pool._in_use += 5
            rec = memory.census("serving_sync")
        # headroom / slope: (101-1-40)*100 B left, growing 500 B/census
        assert rec["steps_to_exhaustion"] == pytest.approx(
            (101 - 1 - 40) * 100 / 500, rel=0.01)
        memory.reset()
        memory.register_kv_pool(pool)
        for _ in range(6):                    # flat: no trend
            rec = memory.census("serving_sync")
        assert rec["steps_to_exhaustion"] is None
        assert memory.forecast() is None

    def test_census_fields_gauges_and_sentinel(self):
        pool = FakePool(in_use=8)
        memory.register_kv_pool(pool)
        fields = memory.census_fields("router_gap")
        assert fields["kv_occupancy"] == pytest.approx(0.8)
        assert "steps_to_exhaustion" not in fields   # no trend yet
        assert _gauge("pt_memory_live_bytes", pool="total") is not None
        assert _gauge("pt_memory_live_bytes", pool="kv_pages") == \
            pool.pool_bytes
        assert _gauge("pt_memory_kv_occupancy") == pytest.approx(0.8)
        assert _gauge("pt_memory_kv_headroom_bytes") == \
            2 * pool.page_bytes
        # the gauge's no-trend sentinel is -1, never an absent series
        assert _gauge("pt_memory_steps_to_exhaustion") == -1

    def test_ledger_records_static_then_census(self):
        memory.record_static("hapi.grad_step", {"argument": 4})
        memory.census("fit_step")
        recs = memory.ledger_records()
        kinds = [r["kind"] for r in recs]
        assert kinds == ["static", "census"]
        assert recs[0]["surface"] == "hapi.grad_step"
        assert recs[1]["point"] == "fit_step"


# -- hbm_pressure watch rule -----------------------------------------------

class TestHbmPressureRule:
    def _eng(self, **kw):
        kw.setdefault("rules", ("hbm_pressure",))
        kw.setdefault("hbm_min_samples", 2)
        kw.setdefault("cooldown_s", 0.0)
        return watch.WatchEngine(watch.WatchConfig(**kw))

    def test_occupancy_trip(self):
        eng = self._eng()
        s = {"point": "serving_sync", "kv_occupancy": 0.95,
             "kv_headroom_bytes": 100}
        assert eng.evaluate(dict(s)) == []    # below min samples
        (a,) = eng.evaluate(dict(s))
        assert a["rule"] == "hbm_pressure"
        assert a["value"] == pytest.approx(0.95)
        assert "occupancy" in a["detail"]

    def test_forecast_trip(self):
        eng = self._eng()
        s = {"point": "fit_step", "kv_occupancy": 0.5,
             "steps_to_exhaustion": 12.0}
        eng.evaluate(dict(s))
        (a,) = eng.evaluate(dict(s))
        assert a["rule"] == "hbm_pressure"
        assert "OOM forecast" in a["detail"]

    def test_needs_census_bearing_samples(self):
        eng = self._eng()
        # census-free samples never advance the rule
        for _ in range(8):
            assert eng.evaluate({"point": "serving_sync",
                                 "queue_depth": 0}) == []
        assert eng.state_summary()["hbm_samples"] == 0

    def test_only_census_sync_points(self):
        eng = self._eng()
        for _ in range(4):
            alerts = eng.evaluate({"point": "request",
                                   "kv_occupancy": 0.99,
                                   "ttft_ms": 1.0, "tpot_ms": 1.0,
                                   "replica": None})
        assert alerts == []


# -- chaos e2e -------------------------------------------------------------

class TestChaosPoolShrink:
    def test_shrink_trips_bundle_and_doctor(self, tmp_path):
        """Shrink the page pool mid-run: hbm_pressure trips, ONE bundle
        is written carrying memory.jsonl, and doctor ranks
        memory_pressure as the top cause."""
        d = str(tmp_path / "flight")
        flight.enable(dump_dir=d, dump_async=False,
                      config=watch.WatchConfig(
                          rules=("hbm_pressure",), hbm_min_samples=2,
                          cooldown_s=0.0))
        mgr = _mgr(num_pages=9)
        memory.record_static("serving.paged_decode_chunk",
                             {"argument": 64, "output": 32})
        tripped = []
        for i in range(6):
            if i == 3:
                mgr._free = []                # pool shrink: 8/8 in use
            fields = memory.census_fields("serving_sync")
            tripped += flight.record("serving_sync", decoded=i,
                                     **fields)
        assert any(a["rule"] == "hbm_pressure" for a in tripped)
        bundles = [n for n in os.listdir(d) if n.startswith("bundle_")]
        assert len(bundles) == 1              # cooldown coalesces
        bdir = os.path.join(d, bundles[0])
        mem_lines = [json.loads(l) for l in
                     open(os.path.join(bdir, "memory.jsonl"),
                          encoding="utf-8")]
        assert any(r["kind"] == "static" and
                   r["surface"] == "serving.paged_decode_chunk"
                   for r in mem_lines)
        census = [r for r in mem_lines if r["kind"] == "census"]
        assert census and census[-1]["kv_occupancy"] >= 0.87
        result = doctor.diagnose(doctor.load_bundle(bdir))
        top = result["diagnoses"][0]
        assert top["cause"] == "memory_pressure"
        assert any("occupancy" in e for e in top["evidence"])

    def test_doctor_cli_names_memory_pressure(self, tmp_path, capsys):
        d = str(tmp_path / "flight")
        flight.enable(dump_dir=d, dump_async=False,
                      config=watch.WatchConfig(
                          rules=("hbm_pressure",), hbm_min_samples=2,
                          cooldown_s=0.0))
        pool = FakePool(in_use=10)            # 100% occupancy
        memory.register_kv_pool(pool)
        for _ in range(3):
            flight.record("router_gap",
                          **memory.census_fields("router_gap"))
        (bundle,) = flight.recorder().dumps()
        assert report.main(["doctor", bundle]) == 0
        assert "memory_pressure" in capsys.readouterr().out


# -- satellite 3: bundle retention under concurrent trips -------------------

class TestBundleRetention:
    def test_concurrent_trips_coalesce_to_one_bundle(self, tmp_path):
        d = str(tmp_path / "flight")
        rec = flight.FlightRecorder(
            dump_dir=d, dump_async=False, dump_cooldown_s=120.0,
            config=watch.WatchConfig(
                rules=("guardian_escalation", "straggler_replica"),
                cooldown_s=0.0))
        barrier = threading.Barrier(2)

        def trip_rollback():
            barrier.wait()
            rec.record("fit_step", verdict="rollback", step=1)

        def trip_straggler():
            barrier.wait()
            rec.record("router_gap", stale_replicas=1, queue_depth=0)

        ts = [threading.Thread(target=trip_rollback),
              threading.Thread(target=trip_straggler)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # two different rules tripped near-simultaneously; the global
        # dump cooldown coalesces the incident into exactly one bundle
        assert len([n for n in os.listdir(d)
                    if n.startswith("bundle_")]) == 1
        assert len(rec.dumps()) == 1

    def test_retention_spares_midwrite_tmp_dirs(self, tmp_path):
        d = str(tmp_path / "flight")
        os.makedirs(d)
        # a concurrent dump mid-write: dot-tmp dirs are invisible to
        # the keep-last-K sweep (only published bundle_* names count)
        midwrite = os.path.join(d, ".bundle_1_hbm_pressure.tmp")
        os.makedirs(midwrite)
        with open(os.path.join(midwrite, "meta.json"), "w") as f:
            f.write("{}")
        rec = flight.FlightRecorder(dump_dir=d, dump_async=False,
                                    keep=1, dump_cooldown_s=0.0)
        first = rec.dump(trigger="manual")
        time.sleep(0.002)                     # distinct ns timestamps
        second = rec.dump(trigger="manual")
        assert os.path.isdir(midwrite)        # never swept mid-write
        bundles = [n for n in os.listdir(d) if n.startswith("bundle_")]
        assert bundles == [os.path.basename(second)]
        assert not os.path.exists(first)


# -- satellite 1: dropped-span accounting ----------------------------------

class TestDroppedSpans:
    def test_ring_overflow_ticks_counter(self, monkeypatch):
        monkeypatch.setattr(tracing, "_SPANS",
                            collections.deque(maxlen=2))
        t0 = time.perf_counter_ns()
        for i in range(5):
            tracing.span(f"t{i}", i, "decode", t0, t0 + 1000, tokens=2)
        assert tracing.dropped_spans() == 3
        assert _gauge("pt_trace_dropped_spans_total") == 3

    def test_report_requests_flags_tiling_violation(self, tmp_path,
                                                    monkeypatch,
                                                    capsys):
        monkeypatch.setattr(tracing, "_SPANS",
                            collections.deque(maxlen=2))
        t0 = time.perf_counter_ns()
        ms = 1_000_000
        tracing.span("t1-r0", 0, "prefill", t0, t0 + 5 * ms, tokens=1)
        tracing.span("t1-r0", 0, "decode", t0 + 5 * ms, t0 + 9 * ms,
                     tokens=4)
        tracing.span("t2-r1", 1, "prefill", t0, t0 + 3 * ms, tokens=1)
        assert tracing.dropped_spans() == 1
        path = str(tmp_path / "trace.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": timeline.merged_trace_events(
                include_profiler=False, include_guardian=False,
                include_samples=False)}, f)
        assert report.dropped_spans_from_trace(path) == 1
        assert report.main(["report", "--trace", path,
                            "--requests"]) == 0
        out = capsys.readouterr().out
        assert "WARNING" in out and "span-tiling invariant" in out
        assert "pt_trace_dropped_spans_total" in out

    def test_clean_run_no_flag(self, tmp_path, capsys):
        t0 = time.perf_counter_ns()
        ms = 1_000_000
        tracing.span("t3-r0", 0, "prefill", t0, t0 + 2 * ms, tokens=1)
        path = str(tmp_path / "trace.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": timeline.merged_trace_events(
                include_profiler=False, include_guardian=False,
                include_samples=False)}, f)
        assert report.dropped_spans_from_trace(path) == 0
        assert report.main(["report", "--trace", path,
                            "--requests"]) == 0
        assert "WARNING" not in capsys.readouterr().out


# -- satellite 2: guardian clock offset with no capture ---------------------

class TestGuardianClockOffset:
    def test_offset_minted_once_and_reused(self):
        old_pair = metrics._CLOCK_PAIR[0]
        old_fallback = timeline._FALLBACK_PAIR[0]
        metrics._CLOCK_PAIR[0] = None         # no capture ran
        timeline._FALLBACK_PAIR[0] = None
        try:
            guardian.emit("flight_dump", trigger="manual", path="/x",
                          alerts=0, kept=1)

            def guardian_ts():
                evs = timeline.merged_trace_events(
                    include_profiler=False, include_samples=False,
                    include_requests=False)
                return [e["ts"] for e in evs
                        if e.get("cat") == "guardian"]
            first = guardian_ts()
            assert first
            time.sleep(0.01)
            # a second export must reuse the SAME minted (wall, perf)
            # pair — re-minting would shift every guardian instant by
            # the time between exports
            assert guardian_ts() == first
        finally:
            metrics._CLOCK_PAIR[0] = old_pair
            timeline._FALLBACK_PAIR[0] = old_fallback

    def test_timeline_memory_counter_tracks(self):
        pool = FakePool(in_use=6)
        memory.register_kv_pool(pool)
        memory.census_fields("fit_step")
        evs = timeline.merged_trace_events(include_profiler=False,
                                           include_guardian=False,
                                           include_requests=False)
        names = {e["name"] for e in evs if e.get("cat") == "memory"}
        assert "pt_memory_live_bytes{pool=kv_pages}" in names
        assert "pt_memory_kv_occupancy" in names


# -- satellite 5: bench gate requires memory.json ---------------------------

class TestBenchGateMemoryArtifact:
    def test_required_next_to_bench_artifacts(self, tmp_path):
        from paddle_tpu.analysis import bench_gate
        root = str(tmp_path)
        assert bench_gate.missing_memory_artifact(root) == []
        with open(os.path.join(root, "BENCH_r01.json"), "w") as f:
            json.dump({"metric": "tokens_per_sec", "value": 1.0}, f)
        rows = bench_gate.missing_memory_artifact(root)
        assert rows and rows[0][0] == bench_gate.MEMORY_ARTIFACT
        # a full snapshot (placeholder rows included) satisfies it
        memory.write_memory_json(
            os.path.join(root, "telemetry", "memory.json"))
        assert bench_gate.missing_memory_artifact(root) == []

    def test_flags_each_missing_surface(self, tmp_path):
        from paddle_tpu.analysis import bench_gate
        root = str(tmp_path)
        with open(os.path.join(root, "BENCH_r01.json"), "w") as f:
            json.dump({"metric": "tokens_per_sec", "value": 1.0}, f)
        path = memory.write_memory_json(
            os.path.join(root, "telemetry", "memory.json"))
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        del doc["surfaces"]["generation.decode"]
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        rows = bench_gate.missing_memory_artifact(root)
        assert [(r[1]) for r in rows] == ["generation.decode"]

    def test_committed_artifact_is_valid(self):
        """The repo's own committed telemetry/memory.json must satisfy
        the gate it ships (every registry surface has a static row)."""
        from paddle_tpu.analysis import bench_gate
        assert bench_gate.missing_memory_artifact(REPO) == []


# -- report --memory --------------------------------------------------------

class TestReportMemory:
    def test_memory_view_from_artifact(self, tmp_path):
        memory.record_static("hapi.train_step",
                             {"argument": 100, "output": 28})
        pool = FakePool(in_use=4)
        memory.register_kv_pool(pool)
        memory.census("serving_sync")
        path = memory.write_memory_json(str(tmp_path / "memory.json"))
        view = report.memory_view(memory_json=path)
        assert view["static"]["hapi.train_step"]["total_bytes"] == 128
        assert view["live"]["kv_occupancy"] == pytest.approx(0.4)
        text = report.render_memory(view)
        assert "hapi.train_step" in text
        assert "(not compiled this run)" in text

    def test_memory_view_from_prom(self, tmp_path):
        memory.record_static("serving.prefill", {"argument": 64})
        pool = FakePool(in_use=2)
        memory.register_kv_pool(pool)
        memory.census_fields("serving_sync")
        prom = str(tmp_path / "m.prom")
        export.write_prometheus(prom)
        view = report.memory_view(prom=prom)
        assert view["static"]["serving.prefill"]["total_bytes"] == 64
        assert view["live"]["kv_occupancy"] == pytest.approx(0.2)
        # -1 forecast sentinel is filtered, not rendered as a forecast
        assert "steps_to_exhaustion" not in view["live"]

    def test_no_data_discipline(self, tmp_path, capsys):
        missing = str(tmp_path / "nope.json")
        assert report.main(["report", "--memory",
                            "--memory-json", missing]) == 0
        assert "no data: memory" in capsys.readouterr().out
        assert report.main(["report", "--memory"]) == 2
