"""Meta-optimizer tests (reference pattern:
test/collective/fleet/test_fleet_lars_meta_optimizer.py,
test_fleet_dgc_meta_optimizer.py, test_fleet_gradient_merge_meta_optimizer
.py, test_fleet_localsgd_meta_optimizer.py — strategy flags must change
the applied update rule, with numeric parity checks)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.core import Tensor
from paddle_tpu.optimizer import LarsMomentum, DGCMomentum, Momentum, SGD
from paddle_tpu.distributed.fleet.base.distributed_strategy import (
    DistributedStrategy)
from paddle_tpu.distributed.fleet.meta_optimizers import (
    apply_meta_optimizers, GradientMergeHelper, LocalSGDOptimizer)
from paddle_tpu.distributed.fleet.meta_parallel import (
    HybridParallelOptimizer)


def _param(arr):
    p = Tensor(jnp.asarray(arr), stop_gradient=False)
    p.is_parameter = True
    return p


def test_lars_update_matches_manual():
    rng = np.random.RandomState(0)
    w0 = rng.randn(4, 4).astype("f4")
    g = rng.randn(4, 4).astype("f4")
    p = _param(w0)
    p._grad = jnp.asarray(g)
    opt = LarsMomentum(learning_rate=0.1, momentum=0.9, lars_coeff=0.001,
                       lars_weight_decay=0.0005, parameters=[p])
    opt.step()

    w_norm = np.linalg.norm(w0)
    g_norm = np.linalg.norm(g)
    local_lr = 0.1 * 0.001 * w_norm / (1e-9 + g_norm + 0.0005 * w_norm)
    v = local_lr * (g + 0.0005 * w0)
    np.testing.assert_allclose(np.asarray(p._value), w0 - v, rtol=1e-5)
    # second step uses momentum-carried velocity
    p._grad = jnp.asarray(g)
    opt.step()
    w1 = w0 - v
    w_norm1 = np.linalg.norm(w1)
    local_lr1 = 0.1 * 0.001 * w_norm1 / (
        1e-9 + g_norm + 0.0005 * w_norm1)
    v1 = 0.9 * v + local_lr1 * (g + 0.0005 * w1)
    np.testing.assert_allclose(np.asarray(p._value), w1 - v1, rtol=1e-4)


def test_dgc_topk_and_error_feedback():
    n = 100
    g = np.zeros(n, dtype="f4")
    g[7] = 10.0   # dominant entry
    g[3] = 0.5    # small entry: must stay in the residual
    p = _param(np.zeros(n, dtype="f4"))
    p._grad = jnp.asarray(g)
    opt = DGCMomentum(learning_rate=1.0, momentum=0.0, parameters=[p],
                      sparsity=0.99)  # k = 1
    opt.step()
    w = np.asarray(p._value)
    # only the top-1 entry was applied
    assert w[7] == pytest.approx(-10.0)
    assert w[3] == 0.0
    # error feedback: the unsent entry accumulates and is applied once
    # it becomes the largest residual
    p._grad = jnp.zeros(n)
    for _ in range(2):
        opt.step()
    w = np.asarray(p._value)
    assert w[3] == pytest.approx(-0.5)  # residual eventually delivered


def test_dgc_rampup_is_plain_momentum():
    p = _param(np.ones(8, dtype="f4"))
    p._grad = jnp.full((8,), 2.0)
    opt = DGCMomentum(learning_rate=0.1, momentum=0.9, parameters=[p],
                      sparsity=0.99, rampup_begin_step=100)
    opt.step()
    np.testing.assert_allclose(np.asarray(p._value),
                               np.ones(8) - 0.1 * 2.0, rtol=1e-6)


def test_dgc_rampup_crossing_inside_jit():
    """The rampup→dgc phase switch is a traced step counter, so ONE
    compiled update function crosses rampup_begin_step correctly
    (advisor r2: a Python-branch phase flag froze at trace time)."""
    opt = DGCMomentum(learning_rate=1.0, momentum=0.0,
                      sparsity=0.875,  # k=1 for n=8
                      rampup_begin_step=2)
    w0 = np.zeros(8, dtype="f4")
    g = np.zeros(8, dtype="f4")
    g[5] = 4.0
    g[2] = 1.0

    @jax.jit
    def step(p, st):
        return opt._update(jnp.asarray(p), jnp.asarray(g), st, 1.0)

    st = opt._init_state_for(jnp.asarray(w0))
    p = jnp.asarray(w0)
    # steps 0,1: plain momentum (all entries applied)
    p, st = step(p, st)
    np.testing.assert_allclose(np.asarray(p), -g, rtol=1e-6)
    p, st = step(p, st)
    # step 2: same compiled fn, now top-k phase — only g[5] column moves
    p_before = np.asarray(p)
    p, st = step(p, st)
    delta = np.asarray(p) - p_before
    assert delta[5] != 0.0
    assert delta[2] == 0.0  # small entry held back in residual


def test_lars_exclude_from_weight_decay():
    """Excluded params (e.g. bias/bn) get plain momentum: no wd, no
    layer-adaptive scaling (advisor r2: exclusion list was ignored)."""
    w0 = np.full((4,), 2.0, dtype="f4")
    g = np.full((4,), 0.5, dtype="f4")
    p = _param(w0)
    p.name = "bn_scale_0"
    p._grad = jnp.asarray(g)
    opt = LarsMomentum(learning_rate=0.1, momentum=0.9, lars_coeff=0.001,
                       lars_weight_decay=0.0005, parameters=[p],
                       exclude_from_weight_decay=["bn", "bias"])
    opt.step()
    # plain momentum: w - lr*g, with NO lars_coeff scaling and NO wd
    np.testing.assert_allclose(np.asarray(p._value), w0 - 0.1 * g,
                               rtol=1e-6)
    # functional path honors the same exclusion via param_names
    from paddle_tpu.optimizer.optimizer import apply_functional_with_clip
    opt2 = LarsMomentum(learning_rate=0.1, momentum=0.9,
                        exclude_from_weight_decay=["bias"])
    st = [opt2._init_state_for(jnp.asarray(w0))]
    (new_w,), _ = apply_functional_with_clip(
        opt2, [jnp.asarray(w0)], [jnp.asarray(g)], st, 0.1,
        param_names=["fc_bias_1"])
    np.testing.assert_allclose(np.asarray(new_w), w0 - 0.1 * g, rtol=1e-6)
    # ...and a non-excluded name still gets the adaptive update
    (new_w2,), _ = apply_functional_with_clip(
        opt2, [jnp.asarray(w0)], [jnp.asarray(g)],
        [opt2._init_state_for(jnp.asarray(w0))], 0.1,
        param_names=["fc_weight_1"])
    assert not np.allclose(np.asarray(new_w2), w0 - 0.1 * g)


def test_gradient_merge_parity_with_large_batch():
    """k_steps=4 accumulation == one step on the averaged grad."""
    rng = np.random.RandomState(1)
    w0 = rng.randn(3, 3).astype("f4")
    grads = [rng.randn(3, 3).astype("f4") for _ in range(4)]

    p_gm = _param(w0)
    strategy = DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
    opt = HybridParallelOptimizer(
        SGD(learning_rate=0.1, parameters=[p_gm]), strategy=strategy)
    for g in grads:
        p_gm._grad = jnp.asarray(g)
        opt.step()
        opt.clear_grad()

    p_ref = _param(w0)
    ref = SGD(learning_rate=0.1, parameters=[p_ref])
    p_ref._grad = jnp.asarray(np.mean(grads, axis=0))
    ref.step()
    np.testing.assert_allclose(np.asarray(p_gm._value),
                               np.asarray(p_ref._value), rtol=1e-5)
    # param must NOT move during the first 3 accumulation micro-steps
    p2 = _param(w0)
    opt2 = HybridParallelOptimizer(
        SGD(learning_rate=0.1, parameters=[p2]), strategy=strategy)
    p2._grad = jnp.asarray(grads[0])
    opt2.step()
    np.testing.assert_allclose(np.asarray(p2._value), w0)


def test_localsgd_sync_values_pmean():
    """Per-device divergent params average across the dp axis."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    per_dev = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    def f(v):
        out = LocalSGDOptimizer.sync_values([v], "data")
        return out[0]

    synced = shard_map(f, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))(per_dev)
    np.testing.assert_allclose(np.asarray(synced),
                               np.full((8, 1), 3.5), rtol=1e-6)


def test_localsgd_wrapper_steps_inner():
    p = _param(np.ones(4, dtype="f4"))
    inner = SGD(learning_rate=0.5, parameters=[p])
    opt = LocalSGDOptimizer(inner, k_steps=2)
    p._grad = jnp.full((4,), 1.0)
    opt.step()  # world of 1: sync is identity
    np.testing.assert_allclose(np.asarray(p._value), 0.5 * np.ones(4))
    assert opt._local_steps == 1


def test_strategy_swaps_momentum_for_lars_and_dgc():
    p = _param(np.ones(4, dtype="f4"))
    mom = Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])

    s = DistributedStrategy()
    s.lars = True
    s.lars_configs = {"lars_coeff": 0.002}
    out = apply_meta_optimizers(mom, s)
    assert isinstance(out, LarsMomentum)
    assert out._lars_coeff == 0.002
    assert out._parameter_list == [p]

    s2 = DistributedStrategy()
    s2.dgc = True
    out2 = apply_meta_optimizers(
        Momentum(learning_rate=0.1, parameters=[p]), s2)
    assert isinstance(out2, DGCMomentum)

    s3 = DistributedStrategy()
    s3.localsgd = True
    s3.localsgd_configs = {"k_steps": 4}
    out3 = apply_meta_optimizers(
        Momentum(learning_rate=0.1, parameters=[p]), s3)
    assert isinstance(out3, LocalSGDOptimizer)
    assert out3.k_steps == 4

    # non-Momentum inner optimizers pass through untouched
    sgd = SGD(learning_rate=0.1, parameters=[p])
    assert apply_meta_optimizers(sgd, s) is sgd


def test_fleet_save_persistables(tmp_path):
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.checkpoint import load_state_dict
    fleet.init(is_collective=True)
    paddle.seed(3)
    net = nn.Linear(4, 2)
    dnet = fleet.distributed_model(net)
    out = str(tmp_path / "persist")
    fleet.save_persistables(dirname=out)
    loaded = load_state_dict(out)
    ref = net.state_dict()
    for k, v in ref.items():
        np.testing.assert_allclose(np.asarray(loaded[k]),
                                   np.asarray(v._value))
    fleet.stop_worker()  # no PS registered: clean no-op


def test_hybrid_optimizer_trains_model_with_lars():
    paddle.seed(0)
    net = nn.Linear(4, 2)
    s = DistributedStrategy()
    s.lars = True
    opt = HybridParallelOptimizer(
        Momentum(learning_rate=0.05, momentum=0.9,
                 parameters=net.parameters()), strategy=s)
    x = Tensor(jnp.asarray(np.random.RandomState(0)
                           .randn(8, 4).astype("f4")))
    losses = []
    for _ in range(5):
        out = net(x)
        loss = (out * out).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._value))
    assert losses[-1] < losses[0]
