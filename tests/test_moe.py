"""MoE layer tests (reference pattern: test/collective/fleet moe tests +
numpy-golden routing checks)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.core import Tensor
from paddle_tpu.incubate.distributed.models.moe import (
    MoELayer, ExpertLayer, NaiveGate, GShardGate, SwitchGate)
from paddle_tpu.incubate.distributed.models.moe.gate import _top_k_routing


def test_routing_topk_assigns_by_prob():
    """Every token's top-k experts get its dense dispatch slots (no
    capacity pressure), combine weights renormalize the top-k probs."""
    rng = np.random.RandomState(0)
    T, E, k = 16, 4, 2
    logits = jnp.asarray(rng.randn(T, E).astype("f4"))
    combine, dispatch, aux = _top_k_routing(logits, k, capacity=T)
    gates = np.asarray(jax.nn.softmax(logits, axis=-1))
    comb = np.asarray(combine)
    for t in range(T):
        top2 = np.argsort(-gates[t])[:k]
        got = set(np.nonzero(comb[t].sum(axis=-1) > 0)[0])
        assert got == set(top2)
        w = comb[t].sum(axis=-1)[top2]
        expect = gates[t][top2] / gates[t][top2].sum()
        np.testing.assert_allclose(w, expect, rtol=1e-5)
    assert float(aux) > 0


def test_routing_respects_capacity():
    """With capacity 1 an expert serves at most 1 token per choice rank."""
    T, E = 8, 2
    # all tokens prefer expert 0
    logits = jnp.asarray(np.tile([5.0, 0.0], (T, 1)).astype("f4"))
    combine, dispatch, _ = _top_k_routing(logits, 1, capacity=4)
    served = np.asarray(dispatch).sum(axis=(0, 2))
    assert served[0] <= 4  # drops beyond capacity
    # each (expert, slot) holds at most one token
    per_slot = np.asarray(dispatch).sum(axis=0)
    assert per_slot.max() <= 1


def _make_moe(E=4, M=8, H=16, gate=None, seed=0):
    paddle.seed(seed)
    experts = [ExpertLayer(M, H) for _ in range(E)]
    return MoELayer(d_model=M, experts=experts, gate=gate)


def test_moe_forward_matches_manual_dense():
    """Stacked fast path == explicit per-expert numpy computation."""
    rng = np.random.RandomState(1)
    moe = _make_moe(E=2, M=4, H=8, gate={"type": "naive", "top_k": 1})
    moe.gate.capacity_factor = 4.0  # headroom: no token drops in this test
    x = rng.randn(3, 5, 4).astype("f4")
    out = moe(Tensor(jnp.asarray(x)))
    assert tuple(out.shape) == (3, 5, 4)

    xv = x.reshape(-1, 4)
    gw = np.asarray(moe.gate.weight._value)
    gates = np.asarray(jax.nn.softmax(jnp.asarray(xv @ gw), -1))
    pick = gates.argmax(-1)
    expect = np.zeros_like(xv)
    for t in range(xv.shape[0]):
        e = pick[t]
        w1 = np.asarray(moe.expert_w1._value[e])
        b1 = np.asarray(moe.expert_b1._value[e])
        w2 = np.asarray(moe.expert_w2._value[e])
        b2 = np.asarray(moe.expert_b2._value[e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(xv[t] @ w1 + b1),
                                   approximate=False))
        expect[t] = (h @ w2 + b2) * 1.0  # top-1 combine weight == 1
    np.testing.assert_allclose(np.asarray(out._value).reshape(-1, 4),
                               expect, rtol=1e-4, atol=1e-5)


def test_moe_generic_path_matches_stacked():
    class MyExpert(ExpertLayer):
        """Subclass with identical math — must route to the generic
        (loop) path via the exact-type check, and match the fast path."""

    rng = np.random.RandomState(2)
    paddle.seed(7)
    experts_fast = [ExpertLayer(4, 8) for _ in range(2)]
    paddle.seed(7)
    experts_slow = [MyExpert(4, 8) for _ in range(2)]
    paddle.seed(3)
    moe_fast = MoELayer(4, experts_fast, gate={"type": "naive", "top_k": 2})
    paddle.seed(3)
    moe_slow = MoELayer(4, experts_slow, gate={"type": "naive", "top_k": 2})
    assert moe_fast._stacked and not moe_slow._stacked
    x = Tensor(jnp.asarray(rng.randn(6, 4).astype("f4")))
    o_fast = moe_fast(x)
    o_slow = moe_slow(x)
    np.testing.assert_allclose(np.asarray(o_fast._value),
                               np.asarray(o_slow._value),
                               rtol=1e-4, atol=1e-5)


def test_moe_grads_flow_to_experts_and_gate():
    rng = np.random.RandomState(3)
    moe = _make_moe(E=2, M=4, H=8, gate={"type": "gshard", "top_k": 2})
    x = Tensor(jnp.asarray(rng.randn(6, 4).astype("f4")))
    out = moe(x)
    loss = (out * out).sum() + moe.gate.get_loss()
    loss.backward()
    assert moe.expert_w1.grad is not None
    assert float(jnp.abs(moe.expert_w1.grad._value).sum()) > 0
    assert moe.gate.weight.grad is not None
    assert float(jnp.abs(moe.gate.weight.grad._value).sum()) > 0


def test_moe_expert_parallel_sharding_compiles():
    """EP as GSPMD: jit the MoE forward over an 8-device mesh with the
    expert dim sharded; result matches the unsharded eager run."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    rng = np.random.RandomState(4)
    E, M, H = 8, 4, 8
    moe = _make_moe(E=E, M=M, H=H, gate={"type": "naive", "top_k": 2})
    x = jnp.asarray(rng.randn(16, M).astype("f4"))
    ref = moe(Tensor(x))

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("model",))
    params = [moe.gate.weight, moe.expert_w1, moe.expert_b1,
              moe.expert_w2, moe.expert_b2]
    sharded_vals = []
    for p in params:
        spec = getattr(p, "pspec", None) or (None,) * len(p.shape)
        sharded_vals.append(jax.device_put(
            p._value, NamedSharding(mesh, P(*spec))))

    def step(xv, gw, w1, b1, w2, b2):
        out, aux = moe._moe_fn_stacked(xv, gw, w1, b1, w2, b2)
        return out

    with mesh:
        out = jax.jit(step)(x, *sharded_vals)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref._value),
                               rtol=1e-4, atol=1e-5)


def test_sparse_dispatch_matches_dense():
    """Scatter/gather dispatch == dense one-hot einsum dispatch on the
    same routing decisions, including under capacity pressure (drops)."""
    rng = np.random.RandomState(5)
    E, M, H, T = 8, 16, 32, 64
    paddle.seed(11)
    moe = _make_moe(E=E, M=M, H=H, gate={"type": "gshard", "top_k": 2},
                    seed=11)
    # tight capacity so some tokens drop
    moe.gate.capacity_factor = 1.0
    x = jnp.asarray(rng.randn(T, M).astype("f4"))
    params = [p._value for p in (moe.gate.weight, moe.expert_w1,
                                 moe.expert_b1, moe.expert_w2,
                                 moe.expert_b2)]
    dense, aux_d = moe._moe_fn_stacked(x, *params)
    sparse, aux_s = moe._moe_fn_stacked_sparse(x, *params)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux_s), float(aux_d), rtol=1e-5)
    # auto mode picks sparse at E=8
    assert moe._use_sparse()


def test_sparse_dispatch_grads_flow():
    rng = np.random.RandomState(6)
    moe = _make_moe(E=8, M=8, H=16, gate={"type": "gshard", "top_k": 2})
    assert moe._use_sparse()
    x = Tensor(jnp.asarray(rng.randn(32, 8).astype("f4")))
    out = moe(x)
    loss = (out * out).sum() + moe.gate.get_loss()
    loss.backward()
    for p in (moe.expert_w1, moe.expert_w2, moe.gate.weight):
        assert p.grad is not None
        assert float(jnp.abs(p.grad._value).sum()) > 0


def test_sparse_dispatch_e32_mesh_parity():
    """E=32 sharded over the 8-device expert axis == unsharded eager."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    rng = np.random.RandomState(7)
    E, M, H, T = 32, 8, 16, 128
    moe = _make_moe(E=E, M=M, H=H, gate={"type": "gshard", "top_k": 2})
    assert moe._use_sparse()
    x = jnp.asarray(rng.randn(T, M).astype("f4"))
    ref = moe(Tensor(x))

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("model",))
    params = [moe.gate.weight, moe.expert_w1, moe.expert_b1,
              moe.expert_w2, moe.expert_b2]
    sharded_vals = []
    for p in params:
        spec = getattr(p, "pspec", None) or (None,) * len(p.shape)
        sharded_vals.append(jax.device_put(
            p._value, NamedSharding(mesh, P(*spec))))

    def step(xv, *ps):
        out, _ = moe._moe_fn_stacked_sparse(xv, *ps)
        return out

    with mesh:
        out = jax.jit(step)(x, *sharded_vals)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref._value),
                               rtol=1e-4, atol=1e-5)


def test_sparse_dispatch_flops_scale_linearly():
    """Dense dispatch is O(T*E*C*M) = O(T^2) with factor-based capacity;
    sparse scatter/gather is O(T*K*M).  Assert the compiled sparse
    forward spends far fewer FLOPs than the dense one at scale, i.e.
    dispatch is no longer the dominant term (VERDICT r1 weak #4)."""
    rng = np.random.RandomState(8)
    E, M, H, T = 32, 16, 32, 1024
    moe = _make_moe(E=E, M=M, H=H, gate={"type": "gshard", "top_k": 2})
    x = jnp.asarray(rng.randn(T, M).astype("f4"))
    params = [p._value for p in (moe.gate.weight, moe.expert_w1,
                                 moe.expert_b1, moe.expert_w2,
                                 moe.expert_b2)]

    def flops(fn):
        lowered = jax.jit(lambda xv, *ps: fn(xv, *ps)[0]).lower(x, *params)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):   # jax 0.4.x: per-device list
            ca = ca[0]
        return ca["flops"]

    f_dense = flops(moe._moe_fn_stacked)
    f_sparse = flops(moe._moe_fn_stacked_sparse)
    # expert FFN flops alone: 2 matmuls fwd = 2*2*(E*C)*M*H
    cap = moe.gate.capacity(T)
    ffn = 4 * E * cap * M * H
    assert f_sparse < f_dense / 4, (f_sparse, f_dense)
    # sparse total stays within a small multiple of the pure FFN cost
    assert f_sparse < 8 * ffn, (f_sparse, ffn)


def test_switch_and_gshard_gates_smoke():
    for gate in ({"type": "switch"}, {"type": "gshard"},
                 SwitchGate(4, 2), GShardGate(4, 2)):
        moe = _make_moe(E=2, M=4, H=8, gate=gate)
        x = Tensor(jnp.asarray(np.random.RandomState(0)
                               .randn(5, 4).astype("f4")))
        out = moe(x)
        assert tuple(out.shape) == (5, 4)
        assert moe.gate.get_loss() is not None
