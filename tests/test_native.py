"""Native C++ runtime layer tests: TCPStore, BlockingQueue, host tracer,
multiprocess DataLoader (paddle_tpu/csrc/; reference:
paddle/fluid/distributed/store/tcp_store.cc, operators/reader/,
platform/profiler/host_tracer.cc)."""
import json
import threading
import time

import numpy as np
import pytest

from paddle_tpu.framework import native
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.io.blocking_queue import BlockingQueue


def test_native_library_builds():
    assert native.available(), "native .so should build with baked-in g++"


class TestTCPStore:
    def test_set_get_roundtrip(self):
        master = TCPStore(is_master=True, world_size=1)
        try:
            master.set("alpha", b"\x00\x01binary")
            assert master.get("alpha") == b"\x00\x01binary"
            master.set("s", "text")
            assert master.get("s") == b"text"
        finally:
            master.close()

    def test_get_missing_times_out(self):
        master = TCPStore(is_master=True, world_size=1)
        try:
            with pytest.raises(KeyError):
                master.get("nope", timeout=0.2)
        finally:
            master.close()

    def test_add_counter_and_num_keys(self):
        master = TCPStore(is_master=True, world_size=1)
        try:
            assert master.add("cnt", 1) == 1
            assert master.add("cnt", 5) == 6
            assert master.add("cnt", -2) == 4
            master.set("other", b"x")
            assert master.num_keys() == 2
            assert master.delete_key("other")
            assert not master.delete_key("other")
        finally:
            master.close()

    def test_blocking_get_across_clients(self):
        master = TCPStore(is_master=True, world_size=2)
        client = TCPStore(host="127.0.0.1", port=master.port,
                          world_size=2)
        got = {}

        def getter():
            got["v"] = client.get("late-key", timeout=5.0)

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.15)  # getter should be blocked server-side
        master.set("late-key", b"released")
        t.join(timeout=5)
        assert got.get("v") == b"released"
        client.close()
        master.close()

    def test_barrier(self):
        master = TCPStore(is_master=True, world_size=3)
        clients = [TCPStore(port=master.port, world_size=3)
                   for _ in range(2)]
        order = []

        def arrive(store, idx, delay):
            time.sleep(delay)
            store.barrier("b0", timeout=10.0)
            order.append(idx)

        threads = [threading.Thread(target=arrive, args=args) for args in
                   [(master, 0, 0.0), (clients[0], 1, 0.1),
                    (clients[1], 2, 0.2)]]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert sorted(order) == [0, 1, 2]
        for c in clients:
            c.close()
        master.close()


class TestBlockingQueue:
    def test_fifo_and_capacity_backpressure(self):
        q = BlockingQueue(capacity=2)
        assert q.push(b"a") and q.push(b"b")
        assert not q.push(b"c", timeout=0.1)  # full -> timeout
        assert q.pop() == b"a"
        assert q.push(b"c")
        assert q.pop() == b"b" and q.pop() == b"c"
        q.destroy()

    def test_pop_timeout(self):
        q = BlockingQueue(capacity=1)
        with pytest.raises(TimeoutError):
            q.pop(timeout=0.1)
        q.destroy()

    def test_close_drains_then_ends(self):
        q = BlockingQueue(capacity=4)
        q.push(b"x")
        q.close()
        assert q.pop() == b"x"
        assert q.pop() is None
        assert not q.push(b"y")
        q.destroy()

    def test_producer_consumer_threads(self):
        q = BlockingQueue(capacity=3)
        n = 50
        out = []

        def produce():
            for i in range(n):
                assert q.push(str(i).encode())
            q.close()

        t = threading.Thread(target=produce)
        t.start()
        while True:
            item = q.pop(timeout=5.0)
            if item is None:
                break
            out.append(int(item))
        t.join()
        assert out == list(range(n))
        q.destroy()


class TestHostTracer:
    def test_spans_and_chrome_export(self, tmp_path):
        import paddle_tpu.profiler as profiler
        prof = profiler.Profiler()
        with prof:
            with profiler.RecordEvent("outer_span"):
                time.sleep(0.01)
                with profiler.RecordEvent("inner_span"):
                    time.sleep(0.005)
        path = prof.export(str(tmp_path / "trace.json"))
        data = json.loads(open(path).read())
        names = {e["name"] for e in data["traceEvents"]}
        assert {"outer_span", "inner_span"} <= names
        outer = next(e for e in data["traceEvents"]
                     if e["name"] == "outer_span")
        assert outer["dur"] >= 10_000 * 0.9  # us
        summary = prof.summary()
        assert "outer_span" in summary


class _SquareDataset:
    def __len__(self):
        return 37

    def __getitem__(self, i):
        return np.asarray([i * i], dtype=np.float32), np.asarray(
            i, dtype=np.int64)


class TestMultiProcessDataLoader:
    def test_parity_with_single_process(self):
        from paddle_tpu.io import DataLoader
        ds = _SquareDataset()
        golden = [tuple(np.asarray(t._value) for t in batch)
                  for batch in DataLoader(ds, batch_size=5, num_workers=0)]
        got = [tuple(np.asarray(t._value) for t in batch)
               for batch in DataLoader(ds, batch_size=5, num_workers=2)]
        assert len(golden) == len(got) == 8
        for (gx, gy), (x, y) in zip(golden, got):
            np.testing.assert_array_equal(gx, x)
            np.testing.assert_array_equal(gy, y)

    def test_early_break_shuts_down_cleanly(self):
        from paddle_tpu.io import DataLoader
        import threading as _threading
        before = _threading.active_count()
        for rep in range(3):
            loader = DataLoader(_SquareDataset(), batch_size=2,
                                num_workers=2)
            for i, _ in enumerate(loader):
                if i == 1:
                    break
        import gc
        gc.collect()
        deadline = time.monotonic() + 5.0
        # collector threads must not accumulate across abandoned epochs
        while (_threading.active_count() > before + 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert _threading.active_count() <= before + 1

    def test_worker_exception_surfaces(self):
        from paddle_tpu.io import DataLoader

        class Bad(_SquareDataset):
            def __getitem__(self, i):
                if i == 11:
                    raise ValueError("boom at 11")
                return super().__getitem__(i)

        with pytest.raises(RuntimeError, match="boom at 11"):
            for _ in DataLoader(Bad(), batch_size=4, num_workers=2):
                pass
