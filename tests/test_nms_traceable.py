"""Traceable (padded fixed-size) NMS family inside jit/to_static
(VERDICT r4 #6).  Golden = the ragged host path on the same inputs."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.ops import nms, matrix_nms


def _rand_boxes(rs, n, scale=100.0):
    xy = rs.rand(n, 2) * scale
    wh = rs.rand(n, 2) * (scale / 4) + 1.0
    return np.concatenate([xy, xy + wh], axis=1).astype("f4")


class TestTraceableNMS:
    def test_matches_host_in_to_static(self):
        rs = np.random.RandomState(0)
        b = _rand_boxes(rs, 40)
        s = rs.rand(40).astype("f4")

        host = nms(paddle.to_tensor(b), 0.4,
                   scores=paddle.to_tensor(s)).numpy()

        @paddle.jit.to_static
        def f(bt, st):
            return nms(bt, 0.4, scores=st, top_k=40)

        out = f(paddle.to_tensor(b), paddle.to_tensor(s)).numpy()
        kept = out[out >= 0]
        np.testing.assert_array_equal(kept, host)
        # pad is -1 after the kept count
        assert (out[len(host):] == -1).all()

    def test_top_k_truncation(self):
        rs = np.random.RandomState(1)
        b = _rand_boxes(rs, 30)
        s = rs.rand(30).astype("f4")
        host = nms(paddle.to_tensor(b), 0.5, scores=paddle.to_tensor(s),
                   top_k=5).numpy()

        @paddle.jit.to_static
        def f(bt, st):
            return nms(bt, 0.5, scores=st, top_k=5)

        out = f(paddle.to_tensor(b), paddle.to_tensor(s)).numpy()
        np.testing.assert_array_equal(out[:len(host)], host)

    def test_no_scores_uses_box_order(self):
        rs = np.random.RandomState(2)
        b = _rand_boxes(rs, 16)
        host = nms(paddle.to_tensor(b), 0.3).numpy()

        @paddle.jit.to_static
        def f(bt):
            return nms(bt, 0.3, top_k=16)

        out = f(paddle.to_tensor(b)).numpy()
        np.testing.assert_array_equal(out[out >= 0], host)

    def test_traced_without_top_k_raises(self):
        b = _rand_boxes(np.random.RandomState(3), 8)

        @paddle.jit.to_static
        def f(bt):
            return nms(bt, 0.3)

        paddle.jit.enable_sot(False)   # hard-assert: no eager fallback
        try:
            with pytest.raises(ValueError, match="top_k"):
                f(paddle.to_tensor(b))
        finally:
            paddle.jit.enable_sot(True)

    def test_jit_save_with_nms(self, tmp_path):
        """The point of the exercise: detection postprocessing exports."""
        import paddle_tpu.nn as nn
        import paddle_tpu.jit as jit
        from paddle_tpu.static import InputSpec

        class Post(nn.Layer):
            def forward(self, boxes, scores):
                return nms(boxes, 0.45, scores=scores, top_k=10)

        rs = np.random.RandomState(4)
        b = _rand_boxes(rs, 24)
        s = rs.rand(24).astype("f4")
        net = Post()
        path = str(tmp_path / "post")
        jit.save(net, path,
                 input_spec=[InputSpec([24, 4], "float32"),
                             InputSpec([24], "float32")])
        loaded = jit.load(path)
        out = loaded(paddle.to_tensor(b), paddle.to_tensor(s)).numpy()
        host = nms(paddle.to_tensor(b), 0.45,
                   scores=paddle.to_tensor(s), top_k=10).numpy()
        np.testing.assert_array_equal(out[:len(host)], host)


class TestTraceableMatrixNMS:
    def _inputs(self, rs, N=2, C=3, M=24):
        b = np.stack([_rand_boxes(rs, M) for _ in range(N)])
        s = rs.rand(N, C, M).astype("f4")
        return b, s

    def test_matches_host_in_to_static(self):
        rs = np.random.RandomState(5)
        b, s = self._inputs(rs)
        kw = dict(score_threshold=0.3, post_threshold=0.2,
                  nms_top_k=20, keep_top_k=8, return_index=True)

        h_out, h_idx, h_num = matrix_nms(paddle.to_tensor(b),
                                         paddle.to_tensor(s), **kw)

        @paddle.jit.to_static
        def f(bt, st):
            return matrix_nms(bt, st, **kw)

        out, idx, num = f(paddle.to_tensor(b), paddle.to_tensor(s))
        np.testing.assert_array_equal(num.numpy(), h_num.numpy())
        o, hn = out.numpy(), h_num.numpy()
        ho = h_out.numpy()
        hi, ii = h_idx.numpy().ravel(), idx.numpy().ravel()
        # per image: the first rois_num rows match the host dets
        host_off = 0
        for n in range(len(hn)):
            rows = o[n * 8:(n + 1) * 8][:hn[n]]
            np.testing.assert_allclose(
                rows, ho[host_off:host_off + hn[n]], rtol=1e-5,
                atol=1e-5)
            np.testing.assert_array_equal(
                ii[n * 8:(n + 1) * 8][:hn[n]],
                hi[host_off:host_off + hn[n]])
            # pad rows zeroed / -1
            assert (o[n * 8 + hn[n]:(n + 1) * 8] == 0).all()
            assert (ii[n * 8 + hn[n]:(n + 1) * 8] == -1).all()
            host_off += hn[n]

    def test_gaussian_decay_matches_host(self):
        rs = np.random.RandomState(6)
        b, s = self._inputs(rs, N=1, C=2, M=16)
        kw = dict(score_threshold=0.25, post_threshold=0.15,
                  nms_top_k=16, keep_top_k=6, use_gaussian=True,
                  gaussian_sigma=2.0)
        h_out, h_num = matrix_nms(paddle.to_tensor(b),
                                  paddle.to_tensor(s), **kw)

        @paddle.jit.to_static
        def f(bt, st):
            return matrix_nms(bt, st, **kw)

        out, num = f(paddle.to_tensor(b), paddle.to_tensor(s))
        n = int(h_num.numpy()[0])
        assert int(num.numpy()[0]) == n
        np.testing.assert_allclose(out.numpy()[:n], h_out.numpy()[:n],
                                   rtol=1e-4, atol=1e-5)

    def test_traced_requires_static_topk(self):
        rs = np.random.RandomState(7)
        b, s = self._inputs(rs, N=1, C=2, M=8)

        @paddle.jit.to_static
        def f(bt, st):
            return matrix_nms(bt, st, score_threshold=0.3,
                              post_threshold=0.2, nms_top_k=-1,
                              keep_top_k=-1)

        paddle.jit.enable_sot(False)
        try:
            with pytest.raises(ValueError, match="top_k"):
                f(paddle.to_tensor(b), paddle.to_tensor(s))
        finally:
            paddle.jit.enable_sot(True)
