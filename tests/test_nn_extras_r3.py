"""Round-3 API-surface additions (reference: paddle.nn / paddle.vision
gaps found by a surface sweep): unpooling, fractional pooling, RNNT loss
(numpy-DP golden), adaptive log softmax, pairwise distance, unflatten,
perspective transform."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_max_unpool2d_roundtrip():
    """pool(return_mask) -> unpool puts every max back in place."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype("f4")
    out, idx = F.max_pool2d(paddle.to_tensor(x), 2, stride=2,
                            return_mask=True)
    rec = F.max_unpool2d(out, idx, 2, stride=2)
    assert tuple(rec.shape) == (2, 3, 8, 8)
    # per-plane (paddle mask convention): every pooled value lands at
    # its argmax position within its own (n, c) plane
    rec_p = rec.numpy().reshape(6, -1)
    idx_p = idx.numpy().reshape(6, -1).astype("i8")
    out_p = out.numpy().reshape(6, -1)
    for pl in range(6):
        np.testing.assert_allclose(rec_p[pl][idx_p[pl]], out_p[pl])
        mask = np.zeros(rec_p.shape[1], bool)
        mask[idx_p[pl]] = True
        assert (rec_p[pl][~mask] == 0).all()
    # custom (larger) output_size places values consistently per plane
    rec_big = F.max_unpool2d(out, idx, 2, stride=2, output_size=(10, 10))
    assert tuple(rec_big.shape) == (2, 3, 10, 10)
    # layer wrapper
    rec2 = nn.MaxUnPool2D(2, stride=2)(out, idx)
    np.testing.assert_allclose(rec2.numpy(), rec.numpy())


def test_fractional_max_pool2d():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 9, 9).astype("f4")
    out = F.fractional_max_pool2d(paddle.to_tensor(x), output_size=4,
                                  random_u=0.3)
    assert tuple(out.shape) == (1, 2, 4, 4)
    # every output is the max of SOME region -> must appear in the input
    for v in out.numpy().reshape(-1):
        assert (np.abs(x - v) < 1e-6).any()
    # disjoint regions cover the input: global max must survive
    assert out.numpy().max() == pytest.approx(x.max())
    out_m, idx = F.fractional_max_pool2d(paddle.to_tensor(x), 4,
                                         random_u=0.3, return_mask=True)
    xp = x.reshape(2, -1)
    for pl in range(2):
        np.testing.assert_allclose(
            xp[pl][idx.numpy().reshape(2, -1)[pl].astype('i8')],
            out_m.numpy().reshape(2, -1)[pl])


def _rnnt_golden(lp, lab, blank):
    """Numpy log-space forward DP (Graves 2012), single example."""
    T, U1, V = lp.shape
    U = U1 - 1
    alpha = np.full((T, U1), -np.inf)
    for t in range(T):
        for u in range(U1):
            if t == 0 and u == 0:
                alpha[0, 0] = 0.0
                continue
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, lab[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(cands) if cands else -np.inf
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_rnnt_loss_matches_numpy_dp():
    rng = np.random.RandomState(2)
    B, T, U, V = 2, 5, 3, 6
    logits = rng.randn(B, T, U + 1, V).astype("f4")
    labels = rng.randint(1, V, (B, U)).astype("i4")
    il = np.asarray([T, T - 1], "i4")
    ll = np.asarray([U, U - 1], "i4")
    loss = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                       il, ll, blank=0, fastemit_lambda=0.0,
                       reduction="none")
    lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))
    for b in range(B):
        ref = _rnnt_golden(lp[b, :il[b], :ll[b] + 1], labels[b], 0)
        assert float(loss.numpy()[b]) == pytest.approx(ref, rel=1e-4), b
    # grads flow
    x = paddle.to_tensor(logits, stop_gradient=False)
    F.rnnt_loss(x, paddle.to_tensor(labels), il, ll).backward()
    assert np.isfinite(x.grad.numpy()).all()
    # layer wrapper
    l2 = nn.RNNTLoss(blank=0, fastemit_lambda=0.0, reduction="none")(
        paddle.to_tensor(logits), paddle.to_tensor(labels), il, ll)
    np.testing.assert_allclose(l2.numpy(), loss.numpy(), rtol=1e-6)


def test_adaptive_log_softmax_with_loss():
    paddle.seed(3)
    rng = np.random.RandomState(3)
    B, D, NC = 16, 8, 20
    m = nn.AdaptiveLogSoftmaxWithLoss(D, NC, cutoffs=[4, 10])
    x = paddle.to_tensor(rng.randn(B, D).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, NC, (B,)).astype("i4"))
    out, loss = m(x, y)
    assert tuple(out.shape) == (B,)
    # log-probs: all <= 0, loss = -mean
    assert (out.numpy() <= 1e-5).all()
    assert float(loss) == pytest.approx(-out.numpy().mean(), rel=1e-5)
    # full distribution sums to 1: check via exhaustive label sweep on
    # one sample
    probs = []
    for c in range(NC):
        o, _ = m(x[:1], paddle.to_tensor(np.asarray([c], "i4")))
        probs.append(float(np.exp(o.numpy()[0])))
    assert sum(probs) == pytest.approx(1.0, rel=1e-4)


def test_misc_layers_r3():
    rng = np.random.RandomState(4)
    # Unflatten
    x = paddle.to_tensor(rng.randn(2, 12).astype("f4"))
    assert tuple(nn.Unflatten(1, (3, 4))(x).shape) == (2, 3, 4)
    # PairwiseDistance
    a = paddle.to_tensor(rng.randn(5, 7).astype("f4"))
    b = paddle.to_tensor(rng.randn(5, 7).astype("f4"))
    d = nn.PairwiseDistance()(a, b).numpy()
    ref = np.linalg.norm(a.numpy() - b.numpy() + 1e-6, axis=-1)
    np.testing.assert_allclose(d, ref, rtol=1e-5)
    # ChannelShuffle
    x = paddle.to_tensor(np.arange(8, dtype="f4").reshape(1, 8, 1, 1))
    out = nn.ChannelShuffle(2)(x).numpy().reshape(-1)
    np.testing.assert_allclose(out, [0, 4, 1, 5, 2, 6, 3, 7])
    # AdaptiveMaxPool1D/3D
    x = paddle.to_tensor(rng.randn(1, 2, 12).astype("f4"))
    assert tuple(nn.AdaptiveMaxPool1D(4)(x).shape) == (1, 2, 4)
    x = paddle.to_tensor(rng.randn(1, 2, 8, 8, 8).astype("f4"))
    assert tuple(nn.AdaptiveMaxPool3D(2)(x).shape) == (1, 2, 2, 2, 2)
    # TripletMarginWithDistanceLoss (default L2 == TripletMarginLoss eps0)
    anc = paddle.to_tensor(rng.randn(4, 6).astype("f4"))
    pos = paddle.to_tensor(rng.randn(4, 6).astype("f4"))
    neg = paddle.to_tensor(rng.randn(4, 6).astype("f4"))
    l1 = nn.TripletMarginWithDistanceLoss()(anc, pos, neg)
    dp = np.linalg.norm(anc.numpy() - pos.numpy(), axis=-1)
    dn = np.linalg.norm(anc.numpy() - neg.numpy(), axis=-1)
    ref = np.maximum(dp - dn + 1.0, 0).mean()
    assert float(l1) == pytest.approx(ref, rel=1e-4)
    # RNNCellBase exported
    assert issubclass(nn.LSTMCell, nn.RNNCellBase)


def test_perspective_transform_identity():
    from paddle_tpu.vision import transforms as T
    img = np.random.RandomState(5).rand(8, 8, 3).astype("f4")
    pts = [[0, 0], [7, 0], [7, 7], [0, 7]]
    out = T.perspective(img, pts, pts)   # identity homography
    np.testing.assert_allclose(out, img, atol=1e-5)


def test_distributed_surface_r3():
    """gather / object lists / get_backend / split / batch_isend_irecv
    (reference: paddle.distributed API; TPU mapping: ppermute)."""
    import paddle_tpu.distributed as dist
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
        smap = lambda f, m, i, o: shard_map(f, mesh=m, in_specs=i,
                                            out_specs=o)
    except ImportError:
        from jax.experimental.shard_map import shard_map
        smap = lambda f, m, i, o: shard_map(f, mesh=m, in_specs=i,
                                            out_specs=o)

    assert dist.get_backend() == "XLA"
    objs = [{"a": 1}]
    dist.broadcast_object_list(objs, src=0)
    assert objs == [{"a": 1}]
    out = []
    world = dist.get_world_size()
    dist.scatter_object_list(out, [[f"obj{i}"] for i in range(world)],
                             src=0)
    assert out and out[0][0].startswith("obj")
    g2 = dist.new_group(list(range(4)), axis_name=None)
    with pytest.raises(ValueError):
        dist.scatter_object_list([], [["too"], ["few"]], src=0, group=g2)

    # batch_isend_irecv as ring shift on the 8-device mesh
    dist.init_parallel_env()
    g = dist.new_group(list(range(8)), axis_name="g")
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("g",))
    axis = "g"

    from paddle_tpu.framework.core import Tensor

    def ring(v):
        t = Tensor(v)
        recv_buf = Tensor(jnp.zeros_like(v))
        ops = [dist.P2POp(dist.isend, t, 1, g),
               dist.P2POp(dist.irecv, recv_buf, 7, g)]
        dist.batch_isend_irecv(ops)
        return recv_buf._value

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    shifted = smap(ring, mesh, P(axis), P(axis))(x)
    # every rank sent to rank+1: result is a ring rotation
    np.testing.assert_allclose(np.asarray(shifted).reshape(-1),
                               np.roll(np.arange(8), 1))

    # gather inside the trace
    def gat(v):
        lst = []
        dist.gather(Tensor(v), lst, dst=0, group=g)
        return jnp.stack([t._value if hasattr(t, "_value") else t
                          for t in lst])
    got = smap(gat, mesh, P(axis), P(axis))(x)
    np.testing.assert_allclose(np.asarray(got).reshape(8, 8)[0],
                               np.arange(8))


def test_random_ops_r3():
    paddle.seed(0)
    n = paddle.to_tensor(np.full((5000,), 20, "i4"))
    p = paddle.to_tensor(np.full((5000,), 0.3, "f4"))
    b = paddle.binomial(n, p).numpy()
    assert b.min() >= 0 and b.max() <= 20
    assert abs(b.mean() - 6.0) < 0.3          # E = np = 6
    ln = paddle.log_normal(mean=0.0, std=0.5, shape=[5000]).numpy()
    assert (ln > 0).all()
    assert abs(np.log(ln).mean()) < 0.1
    x = paddle.zeros([1000])
    paddle.cauchy_(x, loc=2.0, scale=1.0)
    assert abs(float(np.median(x.numpy())) - 2.0) < 0.3


def test_triplet_with_distance_grads_flow():
    """Review r3: the default-distance path must keep the tape (it used
    to rebuild raw Tensors and silently zero all gradients)."""
    rng = np.random.RandomState(6)
    a = paddle.to_tensor(rng.randn(4, 6).astype("f4"), stop_gradient=False)
    p = paddle.to_tensor(rng.randn(4, 6).astype("f4"), stop_gradient=False)
    n = paddle.to_tensor(rng.randn(4, 6).astype("f4"), stop_gradient=False)
    loss = F.triplet_margin_with_distance_loss(a, p, n, swap=True)
    loss.backward()
    assert a.grad is not None and p.grad is not None and n.grad is not None
    assert np.abs(a.grad.numpy()).sum() > 0


def test_tensor_inplace_methods_r3():
    """In-place Tensor method family (reference: paddle.Tensor.*_):
    rebind semantics keep the autograd tape intact."""
    x = paddle.to_tensor(np.ones((2,), "f4"), stop_gradient=False)
    y = x * 3.0
    y.add_(paddle.to_tensor(np.ones((2,), "f4")))
    y.scale_(2.0)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])
    t = paddle.to_tensor(np.ones((2, 2), "f4"))
    t.fill_(5.0)
    assert (t.numpy() == 5.0).all()
    t.zero_()
    assert (t.numpy() == 0.0).all()
    t.uniform_(0.0, 1.0)
    assert ((t.numpy() >= 0) & (t.numpy() <= 1)).all()
    assert t.element_size() == 4 and t.nbytes == 16
    t.detach_()
    assert t.stop_gradient


def test_incubate_segment_and_graph_ops():
    import paddle_tpu.incubate as inc
    x = paddle.to_tensor(np.asarray([[1., 2.], [3., 4.], [5., 6.]], "f4"),
                         stop_gradient=False)
    ids = paddle.to_tensor(np.asarray([0, 0, 1], "i4"))
    s = inc.segment_sum(x, ids)
    np.testing.assert_allclose(s.numpy(), [[4., 6.], [5., 6.]])
    np.testing.assert_allclose(inc.segment_mean(x, ids).numpy(),
                               [[2., 3.], [5., 6.]])
    np.testing.assert_allclose(inc.segment_max(x, ids).numpy(),
                               [[3., 4.], [5., 6.]])
    np.testing.assert_allclose(inc.segment_min(x, ids).numpy(),
                               [[1., 2.], [5., 6.]])
    # differentiable
    s.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 2)))
    out = inc.graph_send_recv(
        x, paddle.to_tensor(np.asarray([0, 1, 2], "i4")),
        paddle.to_tensor(np.asarray([1, 1, 0], "i4")), "mean")
    np.testing.assert_allclose(out.numpy(), [[5., 6.], [2., 3.], [0., 0.]])
    m = inc.softmax_mask_fuse_upper_triangle(
        paddle.to_tensor(np.zeros((1, 1, 4, 4), "f4")))
    np.testing.assert_allclose(m.numpy()[0, 0, 0], [1, 0, 0, 0], atol=1e-6)
    assert float(inc.identity_loss(x, "mean")) == pytest.approx(3.5)


def test_incubate_lookahead_and_model_average():
    from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage
    paddle.seed(0)
    lin = nn.Linear(4, 4)
    inner = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    opt = LookAhead(inner, alpha=0.5, k=2)
    w0 = lin.weight.numpy().copy()
    for _ in range(2):
        loss = lin(paddle.ones([2, 4])).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    # after k steps the weight is slow + alpha*(fast - slow)
    assert not np.allclose(lin.weight.numpy(), w0)
    ma = ModelAverage(parameters=lin.parameters())
    v1 = lin.weight.numpy().copy()
    ma.step()
    lin.weight._value = lin.weight._value + 1.0
    ma.step()
    with ma.apply():
        np.testing.assert_allclose(lin.weight.numpy(), v1 + 0.5,
                                   rtol=1e-6)
    np.testing.assert_allclose(lin.weight.numpy(), v1 + 1.0, rtol=1e-6)


def test_static_extras_r3():
    import paddle_tpu.static as static
    x = paddle.to_tensor(np.asarray([3.0], "f4"), stop_gradient=False)
    y = (x * x).sum()
    (g,) = static.gradients([y], [x])
    np.testing.assert_allclose(g.numpy(), [6.0])
    r = static.py_func(lambda a: a * 2 + 1,
                       paddle.to_tensor(np.asarray([1., 2.], "f4")),
                       paddle.zeros([2]))
    np.testing.assert_allclose(r.numpy(), [3., 5.])
    p = static.create_parameter([2, 2], "float32")
    assert not p.stop_gradient and p.is_parameter
    ema = static.ExponentialMovingAverage(0.5)
    p._value = jnp.ones((2, 2))
    ema.update([p])
    p._value = jnp.full((2, 2), 3.0)
    ema.update([p])
    with ema.apply():
        np.testing.assert_allclose(p.numpy(), np.full((2, 2), 2.0))
    np.testing.assert_allclose(p.numpy(), np.full((2, 2), 3.0))
    attr = static.WeightNormParamAttr(dim=0)
    assert attr.dim == 0


def test_misc_surface_r3():
    """iinfo/finfo/flops/rng aliases/amp queries/device stream shims."""
    assert paddle.iinfo("int32").max == 2**31 - 1
    assert paddle.finfo("bfloat16").max > 3e38
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)
    import paddle_tpu.amp as amp
    assert amp.is_bfloat16_supported() and amp.is_float16_supported()
    amp.debugging.check_numerics(paddle.to_tensor(np.ones(3, "f4")))
    with pytest.raises(FloatingPointError):
        amp.debugging.check_numerics(
            paddle.to_tensor(np.asarray([np.inf], "f4")))
    import paddle_tpu.device as device
    s = device.Stream()
    s.synchronize()
    with device.stream_guard(s):
        assert device.current_stream() is s
    assert "cpu" in device.get_all_device_type() or \
        "tpu" in device.get_all_device_type()


def test_flops_via_cost_analysis():
    """paddle.flops reads XLA's compiled cost analysis; LeNet@28x28 is
    ~0.7 MFLOP/img at batch 1 (conv+fc macs x2)."""
    from paddle_tpu.vision.models import LeNet
    fl = paddle.flops(LeNet(), [1, 1, 28, 28])
    assert 3e5 < fl < 3e6, fl


def test_review_fixes_r3b():
    """Review follow-ups: NHWC mask indices, int segment dtype,
    create_parameter init, py_func backward, dtype-stable perspective."""
    import paddle_tpu.static as static
    import paddle_tpu.incubate as inc
    # create_parameter must NOT be all zeros (Xavier init applied)
    p = static.create_parameter([16, 16], "float32")
    assert np.abs(p.numpy()).sum() > 0
    # NHWC mask: spatial index must exclude the channel stride
    x = np.zeros((1, 2, 2, 2), "f4")      # NHWC
    x[0, 1, 1, 0] = 5.0                    # ch0 max at spatial (1,1) -> 3
    x[0, 0, 0, 1] = 7.0                    # ch1 max at spatial (0,0) -> 0
    _, idx = F.max_pool2d(paddle.to_tensor(x), 2, stride=2,
                          return_mask=True, data_format="NHWC")
    assert sorted(idx.numpy().reshape(-1).tolist()) == [0, 3]
    # int segments keep dtype; empty segments fill 0
    xi = paddle.to_tensor(np.asarray([[4], [2]], "i4"))
    ids = paddle.to_tensor(np.asarray([0, 2], "i4"))   # segment 1 empty
    out = inc.segment_max(xi, ids)
    assert str(out.dtype).endswith("int32"), out.dtype
    np.testing.assert_array_equal(out.numpy(), [[4], [0], [2]])
    # py_func custom backward
    # paddle contract: backward_func(*inputs, *outputs, *out_grads)
    xs = paddle.to_tensor(np.asarray([1., 2.], "f4"), stop_gradient=False)
    r2 = static.py_func(lambda a: a * 2, xs, paddle.zeros([2]),
                        backward_func=lambda a, out, g: g * 3)
    r2.sum().backward()
    np.testing.assert_allclose(xs.grad.numpy(), [3., 3.])
    # skip_vars_in_backward_input drops the input from the bwd call
    xs2 = paddle.to_tensor(np.asarray([1., 2.], "f4"), stop_gradient=False)
    r3 = static.py_func(lambda a: a * 2, xs2, paddle.zeros([2]),
                        backward_func=lambda out, g: g * 5,
                        skip_vars_in_backward_input=[xs2])
    r3.sum().backward()
    np.testing.assert_allclose(xs2.grad.numpy(), [5., 5.])
    # RandomPerspective keeps dtype
    from paddle_tpu.vision import transforms as T
    img8 = (np.random.RandomState(0).rand(8, 8, 3) * 255).astype("uint8")
    out8 = T.RandomPerspective(prob=1.0)(img8)
    assert out8.dtype == np.uint8


def test_geometric_namespace():
    """paddle.geometric send_u_recv / send_ue_recv / send_uv parity."""
    import paddle_tpu.geometric as G
    x = paddle.to_tensor(np.asarray([[1., 2.], [3., 4.], [5., 6.]], "f4"),
                         stop_gradient=False)
    e = paddle.to_tensor(np.asarray([[10., 10.], [20., 20.]], "f4"))
    src = np.asarray([0, 1], "i4")
    dst = np.asarray([1, 2], "i4")
    out = G.send_u_recv(x, src, dst, "sum")
    np.testing.assert_allclose(out.numpy(), [[0., 0.], [1., 2.], [3., 4.]])
    out2 = G.send_ue_recv(x, e, src, dst, "add", "sum")
    np.testing.assert_allclose(out2.numpy(),
                               [[0., 0.], [11., 12.], [23., 24.]])
    out3 = G.send_uv(x, x, src, dst, "mul")
    np.testing.assert_allclose(out3.numpy(), [[3., 8.], [15., 24.]])
    out2.sum().backward()
    assert np.isfinite(x.grad.numpy()).all()


def test_lookahead_slow_weights_seeded_and_saved():
    """Review r3b: slow weights seed from the construction-time params
    (first round interpolates toward them) and persist in state_dict."""
    from paddle_tpu.incubate.optimizer import LookAhead
    paddle.seed(1)
    lin = nn.Linear(2, 2)
    w0 = lin.weight.numpy().copy()
    opt = LookAhead(paddle.optimizer.SGD(0.5, parameters=lin.parameters()),
                    alpha=0.5, k=1)
    lin(paddle.ones([1, 2])).sum().backward()
    opt.step()
    # one step, k=1: w = (w0 + w_fast)/2 — NOT w_fast
    fast = w0 - 0.5 * np.ones((2, 2), "f4") * 0  # grad of sum wrt weight = x
    assert not np.allclose(lin.weight.numpy(), w0)
    sd = opt.state_dict()
    assert any(k.startswith("lookahead_slow_") for k in sd)
    # roundtrip keeps the slow copy
    opt2 = LookAhead(paddle.optimizer.SGD(0.5,
                                          parameters=lin.parameters()),
                     alpha=0.5, k=1)
    opt2.set_state_dict(sd)
    assert opt2._steps == 1


def test_inplace_leaf_guard_and_cauchy_detach():
    """Review r3c: grad-requiring leaf in-place raises (paddle
    contract); cauchy_ detaches the producing node like other fillers."""
    w = paddle.to_tensor(np.ones((2,), "f4"), stop_gradient=False)
    with pytest.raises(RuntimeError, match="[Ll]eaf"):
        w.add_(paddle.to_tensor(np.ones((2,), "f4")))
    # no_grad context allows it (manual update loops)
    with paddle.no_grad():
        w.add_(paddle.to_tensor(np.ones((2,), "f4")))
    np.testing.assert_allclose(w.numpy(), [2.0, 2.0])
    # cauchy_ on a derived tensor cuts the tape
    x = paddle.to_tensor(np.ones((4,), "f4"), stop_gradient=False)
    y = x * 2.0
    y.cauchy_()
    y.sum().backward()
    assert x.grad is None


def test_model_average_two_window():
    """ModelAverage window roll: right after max_average_window the
    average still spans the previous window."""
    from paddle_tpu.incubate.optimizer import ModelAverage
    lin = nn.Linear(2, 2)
    ma = ModelAverage(parameters=lin.parameters(), max_average_window=3)
    with paddle.no_grad():
        for v in (1.0, 2.0, 3.0, 10.0):   # 4th step rolls the window
            lin.weight.fill_(v)
            ma.step()
    with ma.apply():
        # average spans ALL 4 samples (old window 1,2,3 + live 10)
        np.testing.assert_allclose(lin.weight.numpy(),
                                   np.full((2, 2), 4.0), rtol=1e-6)


def _lattice_np(lpb, lpe):
    """Numpy transducer DP parameterized by the blank/emit lattices
    directly (single example, full lengths) — the FastEmit surrogate
    reference: L~ = L(lpb, lpe) + lam * L(frozen lpb, lpe)."""
    T, U1 = lpb.shape
    U = U1 - 1
    alpha = np.full((T, U1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U1):
            if t == 0 and u == 0:
                continue
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lpb[t - 1, u])
            if u > 0:
                cands.append(alpha[t, u - 1] + lpe[t, u - 1])
            alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[T - 1, U] + lpb[T - 1, U])


def test_rnnt_fastemit_gradient_finite_difference():
    """FastEmit (VERDICT r3 #7): grad of rnnt_loss(fastemit_lambda=lam)
    must equal the exact gradient of the surrogate
    L + lam * L(stop_grad(blank), emit), finite-differenced in f64."""
    rng = np.random.RandomState(5)
    T, U, V, lam = 3, 2, 4, 0.3
    z0 = rng.randn(T, U + 1, V).astype("f8")
    labels = rng.randint(1, V, (U,)).astype("i4")

    def lsm(z):
        m = z - z.max(-1, keepdims=True)
        return m - np.log(np.exp(m).sum(-1, keepdims=True))

    def split(z):
        lp = lsm(z)
        lpb = lp[:, :, 0]
        lpe = np.stack([lp[:, u, labels[u]] for u in range(U)], 1)
        return lpb, lpe

    lpb0, lpe0 = split(z0)

    def f_full(z):                      # L(lpb(z), lpe(z))
        return _lattice_np(*split(z))

    def f_frozen(z):                    # L(sg(lpb), lpe(z))
        return _lattice_np(lpb0, split(z)[1])

    eps = 1e-5
    ref = np.zeros_like(z0)
    for i in np.ndindex(z0.shape):
        zp, zm = z0.copy(), z0.copy()
        zp[i] += eps
        zm[i] -= eps
        ref[i] = ((f_full(zp) - f_full(zm))
                  + lam * (f_frozen(zp) - f_frozen(zm))) / (2 * eps)

    x = paddle.to_tensor(z0[None].astype("f4"), stop_gradient=False)
    loss = F.rnnt_loss(x, paddle.to_tensor(labels[None]),
                       np.asarray([T], "i4"), np.asarray([U], "i4"),
                       blank=0, fastemit_lambda=lam, reduction="none")
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy()[0], ref, rtol=2e-3,
                               atol=2e-4)
    # identity forward: regularizer must not move the loss value
    plain = F.rnnt_loss(paddle.to_tensor(z0[None].astype("f4")),
                        paddle.to_tensor(labels[None]),
                        np.asarray([T], "i4"), np.asarray([U], "i4"),
                        blank=0, fastemit_lambda=0.0, reduction="none")
    np.testing.assert_allclose(loss.numpy(), plain.numpy(), rtol=1e-6)
    # lam > 0 must actually change the gradient
    x2 = paddle.to_tensor(z0[None].astype("f4"), stop_gradient=False)
    F.rnnt_loss(x2, paddle.to_tensor(labels[None]), np.asarray([T], "i4"),
                np.asarray([U], "i4"), blank=0, fastemit_lambda=0.0,
                reduction="none").backward()
    assert np.abs(x.grad.numpy() - x2.grad.numpy()).max() > 1e-4


def test_segment_ops_traced_ids_num_segments_hint():
    """ADVICE r3: traced segment_ids need an explicit num_segments (XLA
    static shapes); without it the error must be clear, not a
    ConcretizationTypeError."""
    import paddle_tpu.incubate as inc
    x = np.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], "f4")
    ids = np.asarray([0, 0, 1], "i4")

    def traced(v, i):
        return inc.segment_sum(paddle.to_tensor(v), paddle.to_tensor(i),
                               num_segments=2)._value

    out = jax.jit(traced)(jnp.asarray(x), jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), [[4.0, 6.0], [5.0, 6.0]])

    # mean/max/min take the hint too
    def traced_mean(v, i):
        return inc.segment_mean(paddle.to_tensor(v), paddle.to_tensor(i),
                                num_segments=2)._value
    np.testing.assert_allclose(
        np.asarray(jax.jit(traced_mean)(jnp.asarray(x), jnp.asarray(ids))),
        [[2.0, 3.0], [5.0, 6.0]])

    with pytest.raises(ValueError, match="num_segments"):
        jax.jit(lambda v, i: inc.segment_sum(
            paddle.to_tensor(v), paddle.to_tensor(i))._value)(
            jnp.asarray(x), jnp.asarray(ids))


def test_batch_isend_irecv_rejects_inconsistent_shift():
    """ADVICE r3: a batch whose send and recv peers imply different
    rotations must be rejected (the SPMD lowering can only bake one
    uniform shift), not silently mistraced."""
    import paddle_tpu.distributed as dist
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map as smap
    from paddle_tpu.framework.core import Tensor

    dist.init_parallel_env()
    g = dist.new_group(list(range(8)), axis_name="g2")
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("g2",))

    def bad(v):
        t = Tensor(v)
        recv_buf = Tensor(jnp.zeros_like(v))
        # send to rank+1 but claim to receive from rank+2
        ops = [dist.P2POp(dist.isend, t, 1, g),
               dist.P2POp(dist.irecv, recv_buf, 2, g)]
        dist.batch_isend_irecv(ops)
        return recv_buf._value

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    with pytest.raises(ValueError, match="uniform shift|same rotation"):
        smap(bad, mesh, P("g2"), P("g2"))(x)


def test_py_func_skip_vars_backward_shapes():
    """ADVICE r3 (adjudicated): skip_vars_in_backward_input only trims
    the backward CALL; backward_func still returns one gradient per
    forward input in forward order — the reference contract (its docs'
    tanh example skips x yet returns dx).  Multi-input + mixed shapes
    exercise the declared callback shapes."""
    from paddle_tpu import static
    x = paddle.to_tensor(np.asarray([1.0, 2.0], "f4"), stop_gradient=False)
    y = paddle.to_tensor(np.asarray([[3.0], [4.0], [5.0]], "f4"),
                         stop_gradient=False)  # different shape than x

    def fwd(a, b):
        return a * float(b.sum())

    # backward sees only x (y skipped) but returns (gx, gy)
    def bwd(a, out, gout):
        return gout * 12.0, np.zeros((3, 1), "f4") + float(
            (gout * a).sum())

    r = static.py_func(fwd, [x, y], paddle.zeros([2]), backward_func=bwd,
                       skip_vars_in_backward_input=[y])
    r.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0, 12.0])
    np.testing.assert_allclose(y.grad.numpy(), np.full((3, 1), 3.0))


def test_batch_isend_irecv_bidirectional_pairs_by_shift():
    """Send/recv ops pair by implied shift, not declaration order: a
    bidirectional exchange declared sends-first must work."""
    import paddle_tpu.distributed as dist
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map as smap
    from paddle_tpu.framework.core import Tensor

    dist.init_parallel_env()
    g = dist.new_group(list(range(8)), axis_name="g3")
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("g3",))

    def bidir(v):
        t = Tensor(v)
        fwd_buf = Tensor(jnp.zeros_like(v))
        bwd_buf = Tensor(jnp.zeros_like(v))
        ops = [dist.P2POp(dist.isend, t, 1, g),          # to rank+1
               dist.P2POp(dist.isend, Tensor(v * 10.0), 7, g),  # to rank-1
               dist.P2POp(dist.irecv, fwd_buf, 7, g),    # from rank-1
               dist.P2POp(dist.irecv, bwd_buf, 1, g)]    # from rank+1
        dist.batch_isend_irecv(ops)
        return fwd_buf._value + bwd_buf._value

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = smap(bidir, mesh, P("g3"), P("g3"))(x)
    expect = np.roll(np.arange(8.0), 1) + 10.0 * np.roll(np.arange(8.0), -1)
    np.testing.assert_allclose(np.asarray(out).reshape(-1), expect)


def test_segment_num_segments_traced_hint_rejected():
    import paddle_tpu.incubate as inc
    x = np.asarray([[1.0], [2.0]], "f4")
    ids = np.asarray([0, 1], "i4")
    with pytest.raises(ValueError, match="static"):
        jax.jit(lambda v, i, m: inc.segment_sum(
            paddle.to_tensor(v), paddle.to_tensor(i),
            num_segments=paddle.to_tensor(m))._value)(
            jnp.asarray(x), jnp.asarray(ids), jnp.asarray(2))
