"""Round-3 API-surface additions (reference: paddle.nn / paddle.vision
gaps found by a surface sweep): unpooling, fractional pooling, RNNT loss
(numpy-DP golden), adaptive log softmax, pairwise distance, unflatten,
perspective transform."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_max_unpool2d_roundtrip():
    """pool(return_mask) -> unpool puts every max back in place."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype("f4")
    out, idx = F.max_pool2d(paddle.to_tensor(x), 2, stride=2,
                            return_mask=True)
    rec = F.max_unpool2d(out, idx, 2, stride=2)
    assert tuple(rec.shape) == (2, 3, 8, 8)
    # per-plane (paddle mask convention): every pooled value lands at
    # its argmax position within its own (n, c) plane
    rec_p = rec.numpy().reshape(6, -1)
    idx_p = idx.numpy().reshape(6, -1).astype("i8")
    out_p = out.numpy().reshape(6, -1)
    for pl in range(6):
        np.testing.assert_allclose(rec_p[pl][idx_p[pl]], out_p[pl])
        mask = np.zeros(rec_p.shape[1], bool)
        mask[idx_p[pl]] = True
        assert (rec_p[pl][~mask] == 0).all()
    # custom (larger) output_size places values consistently per plane
    rec_big = F.max_unpool2d(out, idx, 2, stride=2, output_size=(10, 10))
    assert tuple(rec_big.shape) == (2, 3, 10, 10)
    # layer wrapper
    rec2 = nn.MaxUnPool2D(2, stride=2)(out, idx)
    np.testing.assert_allclose(rec2.numpy(), rec.numpy())


def test_fractional_max_pool2d():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 9, 9).astype("f4")
    out = F.fractional_max_pool2d(paddle.to_tensor(x), output_size=4,
                                  random_u=0.3)
    assert tuple(out.shape) == (1, 2, 4, 4)
    # every output is the max of SOME region -> must appear in the input
    for v in out.numpy().reshape(-1):
        assert (np.abs(x - v) < 1e-6).any()
    # disjoint regions cover the input: global max must survive
    assert out.numpy().max() == pytest.approx(x.max())
    out_m, idx = F.fractional_max_pool2d(paddle.to_tensor(x), 4,
                                         random_u=0.3, return_mask=True)
    xp = x.reshape(2, -1)
    for pl in range(2):
        np.testing.assert_allclose(
            xp[pl][idx.numpy().reshape(2, -1)[pl].astype('i8')],
            out_m.numpy().reshape(2, -1)[pl])


def _rnnt_golden(lp, lab, blank):
    """Numpy log-space forward DP (Graves 2012), single example."""
    T, U1, V = lp.shape
    U = U1 - 1
    alpha = np.full((T, U1), -np.inf)
    for t in range(T):
        for u in range(U1):
            if t == 0 and u == 0:
                alpha[0, 0] = 0.0
                continue
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, lab[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(cands) if cands else -np.inf
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_rnnt_loss_matches_numpy_dp():
    rng = np.random.RandomState(2)
    B, T, U, V = 2, 5, 3, 6
    logits = rng.randn(B, T, U + 1, V).astype("f4")
    labels = rng.randint(1, V, (B, U)).astype("i4")
    il = np.asarray([T, T - 1], "i4")
    ll = np.asarray([U, U - 1], "i4")
    loss = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                       il, ll, blank=0, fastemit_lambda=0.0,
                       reduction="none")
    lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))
    for b in range(B):
        ref = _rnnt_golden(lp[b, :il[b], :ll[b] + 1], labels[b], 0)
        assert float(loss.numpy()[b]) == pytest.approx(ref, rel=1e-4), b
    # grads flow
    x = paddle.to_tensor(logits, stop_gradient=False)
    F.rnnt_loss(x, paddle.to_tensor(labels), il, ll).backward()
    assert np.isfinite(x.grad.numpy()).all()
    # layer wrapper
    l2 = nn.RNNTLoss(blank=0, fastemit_lambda=0.0, reduction="none")(
        paddle.to_tensor(logits), paddle.to_tensor(labels), il, ll)
    np.testing.assert_allclose(l2.numpy(), loss.numpy(), rtol=1e-6)


def test_adaptive_log_softmax_with_loss():
    paddle.seed(3)
    rng = np.random.RandomState(3)
    B, D, NC = 16, 8, 20
    m = nn.AdaptiveLogSoftmaxWithLoss(D, NC, cutoffs=[4, 10])
    x = paddle.to_tensor(rng.randn(B, D).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, NC, (B,)).astype("i4"))
    out, loss = m(x, y)
    assert tuple(out.shape) == (B,)
    # log-probs: all <= 0, loss = -mean
    assert (out.numpy() <= 1e-5).all()
    assert float(loss) == pytest.approx(-out.numpy().mean(), rel=1e-5)
    # full distribution sums to 1: check via exhaustive label sweep on
    # one sample
    probs = []
    for c in range(NC):
        o, _ = m(x[:1], paddle.to_tensor(np.asarray([c], "i4")))
        probs.append(float(np.exp(o.numpy()[0])))
    assert sum(probs) == pytest.approx(1.0, rel=1e-4)


def test_misc_layers_r3():
    rng = np.random.RandomState(4)
    # Unflatten
    x = paddle.to_tensor(rng.randn(2, 12).astype("f4"))
    assert tuple(nn.Unflatten(1, (3, 4))(x).shape) == (2, 3, 4)
    # PairwiseDistance
    a = paddle.to_tensor(rng.randn(5, 7).astype("f4"))
    b = paddle.to_tensor(rng.randn(5, 7).astype("f4"))
    d = nn.PairwiseDistance()(a, b).numpy()
    ref = np.linalg.norm(a.numpy() - b.numpy() + 1e-6, axis=-1)
    np.testing.assert_allclose(d, ref, rtol=1e-5)
    # ChannelShuffle
    x = paddle.to_tensor(np.arange(8, dtype="f4").reshape(1, 8, 1, 1))
    out = nn.ChannelShuffle(2)(x).numpy().reshape(-1)
    np.testing.assert_allclose(out, [0, 4, 1, 5, 2, 6, 3, 7])
    # AdaptiveMaxPool1D/3D
    x = paddle.to_tensor(rng.randn(1, 2, 12).astype("f4"))
    assert tuple(nn.AdaptiveMaxPool1D(4)(x).shape) == (1, 2, 4)
    x = paddle.to_tensor(rng.randn(1, 2, 8, 8, 8).astype("f4"))
    assert tuple(nn.AdaptiveMaxPool3D(2)(x).shape) == (1, 2, 2, 2, 2)
    # TripletMarginWithDistanceLoss (default L2 == TripletMarginLoss eps0)
    anc = paddle.to_tensor(rng.randn(4, 6).astype("f4"))
    pos = paddle.to_tensor(rng.randn(4, 6).astype("f4"))
    neg = paddle.to_tensor(rng.randn(4, 6).astype("f4"))
    l1 = nn.TripletMarginWithDistanceLoss()(anc, pos, neg)
    dp = np.linalg.norm(anc.numpy() - pos.numpy(), axis=-1)
    dn = np.linalg.norm(anc.numpy() - neg.numpy(), axis=-1)
    ref = np.maximum(dp - dn + 1.0, 0).mean()
    assert float(l1) == pytest.approx(ref, rel=1e-4)
    # RNNCellBase exported
    assert issubclass(nn.LSTMCell, nn.RNNCellBase)


def test_perspective_transform_identity():
    from paddle_tpu.vision import transforms as T
    img = np.random.RandomState(5).rand(8, 8, 3).astype("f4")
    pts = [[0, 0], [7, 0], [7, 7], [0, 7]]
    out = T.perspective(img, pts, pts)   # identity homography
    np.testing.assert_allclose(out, img, atol=1e-5)


def test_distributed_surface_r3():
    """gather / object lists / get_backend / split / batch_isend_irecv
    (reference: paddle.distributed API; TPU mapping: ppermute)."""
    import paddle_tpu.distributed as dist
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
        smap = lambda f, m, i, o: shard_map(f, mesh=m, in_specs=i,
                                            out_specs=o)
    except ImportError:
        from jax.experimental.shard_map import shard_map
        smap = lambda f, m, i, o: shard_map(f, mesh=m, in_specs=i,
                                            out_specs=o)

    assert dist.get_backend() == "XLA"
    objs = [{"a": 1}]
    dist.broadcast_object_list(objs, src=0)
    assert objs == [{"a": 1}]
    out = []
    world = dist.get_world_size()
    dist.scatter_object_list(out, [[f"obj{i}"] for i in range(world)],
                             src=0)
    assert out and out[0][0].startswith("obj")
    g2 = dist.new_group(list(range(4)), axis_name=None)
    with pytest.raises(ValueError):
        dist.scatter_object_list([], [["too"], ["few"]], src=0, group=g2)

    # batch_isend_irecv as ring shift on the 8-device mesh
    dist.init_parallel_env()
    g = dist.new_group(list(range(8)), axis_name="g")
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("g",))
    axis = "g"

    from paddle_tpu.framework.core import Tensor

    def ring(v):
        t = Tensor(v)
        recv_buf = Tensor(jnp.zeros_like(v))
        ops = [dist.P2POp(dist.isend, t, 1, g),
               dist.P2POp(dist.irecv, recv_buf, 7, g)]
        dist.batch_isend_irecv(ops)
        return recv_buf._value

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    shifted = smap(ring, mesh, P(axis), P(axis))(x)
    # every rank sent to rank+1: result is a ring rotation
    np.testing.assert_allclose(np.asarray(shifted).reshape(-1),
                               np.roll(np.arange(8), 1))

    # gather inside the trace
    def gat(v):
        lst = []
        dist.gather(Tensor(v), lst, dst=0, group=g)
        return jnp.stack([t._value if hasattr(t, "_value") else t
                          for t in lst])
    got = smap(gat, mesh, P(axis), P(axis))(x)
    np.testing.assert_allclose(np.asarray(got).reshape(8, 8)[0],
                               np.arange(8))


def test_random_ops_r3():
    paddle.seed(0)
    n = paddle.to_tensor(np.full((5000,), 20, "i4"))
    p = paddle.to_tensor(np.full((5000,), 0.3, "f4"))
    b = paddle.binomial(n, p).numpy()
    assert b.min() >= 0 and b.max() <= 20
    assert abs(b.mean() - 6.0) < 0.3          # E = np = 6
    ln = paddle.log_normal(mean=0.0, std=0.5, shape=[5000]).numpy()
    assert (ln > 0).all()
    assert abs(np.log(ln).mean()) < 0.1
    x = paddle.zeros([1000])
    paddle.cauchy_(x, loc=2.0, scale=1.0)
    assert abs(float(np.median(x.numpy())) - 2.0) < 0.3


def test_triplet_with_distance_grads_flow():
    """Review r3: the default-distance path must keep the tape (it used
    to rebuild raw Tensors and silently zero all gradients)."""
    rng = np.random.RandomState(6)
    a = paddle.to_tensor(rng.randn(4, 6).astype("f4"), stop_gradient=False)
    p = paddle.to_tensor(rng.randn(4, 6).astype("f4"), stop_gradient=False)
    n = paddle.to_tensor(rng.randn(4, 6).astype("f4"), stop_gradient=False)
    loss = F.triplet_margin_with_distance_loss(a, p, n, swap=True)
    loss.backward()
    assert a.grad is not None and p.grad is not None and n.grad is not None
    assert np.abs(a.grad.numpy()).sum() > 0
