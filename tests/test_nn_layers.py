import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear():
    l = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    y = l(x)
    assert y.shape == [2, 3]
    np.testing.assert_allclose(
        y.numpy(), x.numpy() @ l.weight.numpy() + l.bias.numpy(),
        rtol=1e-5, atol=1e-6)


def test_conv2d_matches_manual():
    conv = nn.Conv2D(2, 3, 3, padding=1)
    x = paddle.randn([1, 2, 8, 8])
    y = conv(x)
    assert y.shape == [1, 3, 8, 8]
    # compare against explicit correlation at one output position
    w = conv.weight.numpy()
    b = conv.bias.numpy()
    xp = np.pad(x.numpy(), [(0, 0), (0, 0), (1, 1), (1, 1)])
    ref = (xp[0, :, 3:6, 3:6] * w[1]).sum() + b[1]
    np.testing.assert_allclose(y.numpy()[0, 1, 3, 3], ref, rtol=1e-4,
                               atol=1e-5)


def test_maxpool_avgpool():
    x = paddle.to_tensor(np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    mp = F.max_pool2d(x, 2, 2)
    np.testing.assert_allclose(mp.numpy()[0, 0], [[5, 7], [13, 15]])
    ap = F.avg_pool2d(x, 2, 2)
    np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5],
                                                  [10.5, 12.5]])


def test_batchnorm_train_and_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.randn([4, 3, 5, 5])
    bn.train()
    y = bn(x)
    out = y.numpy()
    np.testing.assert_allclose(out.mean(axis=(0, 2, 3)), 0, atol=1e-5)
    np.testing.assert_allclose(out.std(axis=(0, 2, 3)), 1, atol=1e-2)
    # running stats moved
    assert not np.allclose(bn._mean.numpy(), 0)
    bn.eval()
    y2 = bn(x)
    assert y2.shape == y.shape


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 4, 8])
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = paddle.ones([100, 100])
    d.train()
    y = d(x)
    frac = (y.numpy() == 0).mean()
    assert 0.3 < frac < 0.7
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), 1.0)


def test_embedding():
    emb = nn.Embedding(10, 4)
    idx = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
    out = emb(idx)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])


def test_cross_entropy_matches_manual():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor(np.array([0, 1, 2, 3])[:, None])
    loss = F.cross_entropy(logits, labels)
    lp = np.asarray(logits.numpy(), dtype=np.float64)
    lse = np.log(np.exp(lp).sum(-1))
    ref = (lse - lp[np.arange(4), [0, 1, 2, 3]]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4)


def test_cross_entropy_grad():
    logits = paddle.to_tensor(np.random.randn(4, 5).astype("float32"),
                              stop_gradient=False)
    labels = paddle.to_tensor(np.array([1, 0, 3, 2]))
    loss = F.cross_entropy(logits, labels)
    loss.backward()
    p = np.exp(logits.numpy())
    p /= p.sum(-1, keepdims=True)
    onehot = np.eye(5)[[1, 0, 3, 2]]
    np.testing.assert_allclose(logits.grad.numpy(), (p - onehot) / 4,
                               rtol=1e-4, atol=1e-6)


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(m1.state_dict())
    x = paddle.randn([3, 4])
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_save_load(tmp_path):
    m = nn.Linear(4, 2)
    p = str(tmp_path / "model.pdparams")
    paddle.save(m.state_dict(), p)
    sd = paddle.load(p)
    m2 = nn.Linear(4, 2)
    m2.set_state_dict(sd)
    np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy())


def test_layer_hooks_and_apply():
    m = nn.Linear(3, 3)
    calls = []
    h = m.register_forward_post_hook(lambda l, i, o: calls.append(1))
    m(paddle.randn([2, 3]))
    assert calls == [1]
    h.remove()
    m(paddle.randn([2, 3]))
    assert calls == [1]


def test_named_parameters_deterministic():
    m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
    names = [n for n, _ in m.named_parameters()]
    assert names == ["0.weight", "0.bias", "1.weight", "1.bias"]
