"""paddle.nn.utils: weight_norm / spectral_norm / clip_grad_norm_ /
parameter vectorization.

Reference analogues: test/legacy_test/test_weight_norm_hook.py,
test_spectral_norm_op.py, test_clip_grad_norm_.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.utils import (
    weight_norm, remove_weight_norm, spectral_norm, clip_grad_norm_,
    parameters_to_vector, vector_to_parameters)


class TestWeightNorm:
    def test_forward_preserved_and_factors_train(self):
        rng = np.random.RandomState(0)
        lin = nn.Linear(4, 3)
        w0 = np.asarray(lin.weight._value).copy()
        x = rng.randn(2, 4).astype("float32")
        ref = lin(paddle.to_tensor(x)).numpy()
        weight_norm(lin, dim=0)
        assert "weight_v" in lin._parameters
        assert "weight" not in lin._parameters
        out = lin(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        # gradients flow to g and v
        y = paddle.sum(lin(paddle.to_tensor(x)))
        y.backward()
        assert lin.weight_g.grad is not None
        assert lin.weight_v.grad is not None

    def test_remove_restores_weight(self):
        lin = nn.Linear(4, 3)
        w0 = np.asarray(lin.weight._value).copy()
        weight_norm(lin, dim=0)
        remove_weight_norm(lin)
        assert "weight" in lin._parameters
        np.testing.assert_allclose(np.asarray(lin.weight._value), w0,
                                   rtol=1e-5, atol=1e-6)


class TestSpectralNorm:
    def test_unit_spectral_norm(self):
        rng = np.random.RandomState(1)
        lin = nn.Linear(8, 6)
        spectral_norm(lin, n_power_iterations=20)
        lin.train()
        x = rng.randn(2, 8).astype("float32")
        lin(paddle.to_tensor(x))   # run hooks/power iterations
        lin(paddle.to_tensor(x))
        w_eff = np.asarray(lin.weight._value)
        sigma = np.linalg.svd(w_eff, compute_uv=False)[0]
        np.testing.assert_allclose(sigma, 1.0, rtol=1e-2)

    def test_grad_flows_to_orig(self):
        lin = nn.Linear(4, 4)
        spectral_norm(lin)
        x = paddle.to_tensor(np.ones((2, 4), "float32"))
        paddle.sum(lin(x)).backward()
        assert lin.weight_orig.grad is not None


class TestGradUtilities:
    def test_clip_grad_norm(self):
        a = paddle.to_tensor(np.ones(3, "float32"))
        b = paddle.to_tensor(np.ones(4, "float32"))
        a.stop_gradient = b.stop_gradient = False
        loss = 3.0 * paddle.sum(a) + 4.0 * paddle.sum(b)
        loss.backward()
        total = clip_grad_norm_([a, b], max_norm=1.0)
        expected_norm = np.sqrt(3 * 9.0 + 4 * 16.0)
        np.testing.assert_allclose(float(total.numpy()), expected_norm,
                                   rtol=1e-5)
        new_norm = np.sqrt(np.sum(a.grad.numpy() ** 2) +
                           np.sum(b.grad.numpy() ** 2))
        np.testing.assert_allclose(new_norm, 1.0, rtol=1e-4)

    def test_vector_roundtrip(self):
        rng = np.random.RandomState(2)
        ps = [paddle.to_tensor(rng.randn(2, 3).astype("float32")),
              paddle.to_tensor(rng.randn(4).astype("float32"))]
        vec = parameters_to_vector(ps)
        assert vec.shape == [10]
        vector_to_parameters(vec * 2.0, ps)
        np.testing.assert_allclose(ps[0].numpy(),
                                   vec.numpy()[:6].reshape(2, 3) * 2,
                                   rtol=1e-6)
