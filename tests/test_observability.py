"""Unified telemetry layer (ISSUE 5 tentpole): metrics registry,
exporters, merged run timeline, hot-path instrumentation, and the
zero-sync overhead contract.

Acceptance anchors:
- one run, one timeline: an instrumented ``Model.fit`` +
  ``ServingEngine`` session yields a merged chrome trace with host
  spans, guardian events and metric samples on a shared clock, plus
  Prometheus/JSONL sinks the report CLI summarizes;
- zero syncs: device-transfer counts (guardian ``_host_bool`` shim +
  a ``jax.device_get`` shim) are IDENTICAL with telemetry on vs off.
"""
import json
import os
import threading

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import observability as obs
from paddle_tpu.observability import catalog, export, metrics, timeline
from paddle_tpu.framework import failpoints, guardian
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTForPretraining, gpt3_tiny

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_telemetry():
    obs.enable(True)
    obs.get_registry().reset()
    obs.stop_capture()
    obs.tracing.reset()
    obs.compilestats.reset()
    obs.memory.reset()
    failpoints.clear()
    guardian.clear_events()
    yield
    obs.enable(True)
    obs.get_registry().reset()
    obs.stop_capture()
    obs.tracing.reset()
    obs.compilestats.reset()
    obs.memory.reset()
    failpoints.clear()
    guardian.clear_events()


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    return GPTForPretraining(gpt3_tiny())


def _reg_model(seed=3):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=1e-2,
                                        parameters=net.parameters()),
                  nn.MSELoss())
    return model


def _batches(n=6, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(8, 4).astype("float32"),
             rng.randn(8, 2).astype("float32")) for _ in range(n)]


# -- registry primitives ---------------------------------------------------

class TestRegistry:
    def test_counter_labels_and_monotonicity(self):
        c = metrics.MetricsRegistry().counter("pt_x", labelnames=("op",))
        c.inc(op="a")
        c.inc(2, op="a")
        c.inc(op="b")
        assert c.value(op="a") == 3 and c.value(op="b") == 1
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1, op="a")
        with pytest.raises(ValueError, match="labels"):
            c.inc(wrong="a")

    def test_gauge_set_inc_dec(self):
        g = metrics.MetricsRegistry().gauge("pt_g")
        g.set(5.0)
        g.inc()
        g.dec(2)
        assert g.value() == 4.0

    def test_histogram_buckets_sum_count(self):
        h = metrics.MetricsRegistry().histogram("pt_h", buckets=(1, 10))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        (labels, st), = h.series()
        assert labels == {} and st["counts"] == [1, 1, 1]
        assert st["count"] == 3 and st["sum"] == pytest.approx(55.5)

    def test_reregister_same_object_conflict_raises(self):
        reg = metrics.MetricsRegistry()
        a = reg.counter("pt_c", labelnames=("op",))
        assert reg.counter("pt_c", labelnames=("op",)) is a
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("pt_c")
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("pt_c", labelnames=("other",))

    def test_record_against_undeclared_name_raises(self):
        # name built by concatenation so the metrics-registry lint's
        # text scan never sees a matchable bogus literal in this file
        with pytest.raises(KeyError, match="catalog"):
            obs.inc("pt_train_" + "not_a_real_metric_total")

    def test_thread_safety_exact_total(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("pt_t")

        def worker():
            for _ in range(1000):
                c.inc()
        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 8000

    def test_catalog_well_formed_and_instantiable(self):
        assert catalog.METRICS
        for name, spec in catalog.METRICS.items():
            assert name.startswith("pt_") and \
                name.split("_", 2)[1] in catalog.subsystems()
            assert spec["type"] in ("counter", "gauge", "histogram")
            m = metrics._metric(name)     # registers into the default
            assert m.labelnames == tuple(spec.get("labels", ()))

    def test_disabled_records_nothing(self):
        with obs.disabled():
            obs.inc("pt_train_tokens_total", 100)
            obs.observe("pt_train_step_latency_ms", 5.0)
        assert obs.get_registry().get("pt_train_tokens_total") is None \
            or obs.get_registry().get("pt_train_tokens_total").value() == 0


# -- exporters -------------------------------------------------------------

class TestExporters:
    def test_prometheus_exposition_shape(self, tmp_path):
        obs.inc("pt_store_ops_total", 3, op='we"ird\n')
        obs.observe("pt_store_op_latency_ms", 2.0, op="get")
        path = export.write_prometheus(str(tmp_path / "m.prom"))
        text = open(path).read()
        assert "# TYPE pt_store_ops_total counter" in text
        assert 'pt_store_ops_total{op="we\\"ird\\n"} 3' in text
        # cumulative buckets end at +Inf == count
        assert 'pt_store_op_latency_ms_bucket{op="get",le="+Inf"} 1' \
            in text
        assert "pt_store_op_latency_ms_count" in text

    def test_jsonl_sink_and_env_default(self, tmp_path, monkeypatch):
        obs.inc("pt_train_tokens_total", 7)
        p = str(tmp_path / "m.jsonl")
        assert export.write_jsonl(p, run="r1") == p
        recs = [json.loads(line) for line in open(p)]
        (rec,) = [r for r in recs
                  if r["metric"] == "pt_train_tokens_total"]
        assert rec["value"] == 7 and rec["run"] == "r1" \
            and rec["ts_ns"] > 0
        # env-var default sink, the guardian-log pattern
        monkeypatch.setenv(export.JSONL_ENV, str(tmp_path / "env.jsonl"))
        assert export.write_jsonl() == str(tmp_path / "env.jsonl")
        monkeypatch.delenv(export.JSONL_ENV)
        assert export.write_jsonl() is None

    def test_exporter_materializes_device_scalar(self):
        # a device scalar handed to a gauge syncs ONCE, at export time,
        # through the budgeted _materialize funnel
        obs.set_gauge("pt_train_loss", jnp.asarray(1.5))
        (rec,) = [r for r in export.snapshot()
                  if r["metric"] == "pt_train_loss"]
        assert isinstance(rec["value"], float) and rec["value"] == 1.5


# -- hot-path instrumentation ----------------------------------------------

class TestFitInstrumentation:
    def test_fit_records_steps_latency_tokens_loss(self):
        model = _reg_model()
        model.fit(_batches(5), epochs=1, verbose=0)
        reg = obs.get_registry()
        assert reg.get("pt_train_steps_total").value(outcome="ok") == 5
        assert reg.get("pt_train_step_latency_ms").count() == 5
        assert reg.get("pt_train_tokens_total").value() == 5 * 8 * 4
        assert reg.get("pt_train_tokens_per_sec").value() > 0
        assert np.isfinite(reg.get("pt_train_loss").value())

    def test_guardian_skip_counted_as_outcome(self):
        model = _reg_model()
        cfg = guardian.GuardianConfig(skip_limit=10, ckpt_root=None,
                                      loss_spike=False)
        failpoints.set_failpoint("guardian.poison_batch", "skip*1")
        model.fit(_batches(4), epochs=1, verbose=0, guardian=cfg)
        reg = obs.get_registry()
        assert reg.get("pt_train_steps_total").value(outcome="skip") == 1
        assert reg.get("pt_train_steps_total").value(outcome="ok") == 3


class TestServingInstrumentation:
    def test_serving_counters_histograms_occupancy(self, gpt):
        rng = np.random.RandomState(4)
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8, 16))
        budgets = [3, 6, 4]
        for b in budgets:
            eng.submit(rng.randint(0, 1024, (6,)).astype("int32"), b)
        eng.run()
        reg = obs.get_registry()
        assert reg.get("pt_serving_admissions_total").value() == 3
        assert sum(v for _, v in
                   reg.get("pt_serving_prefills_total").series()) == 3
        assert reg.get("pt_serving_decoded_tokens_total").value() \
            == sum(budgets)
        assert reg.get("pt_serving_ttft_ms").count() == 3
        assert reg.get("pt_serving_queue_wait_ms").count() == 3
        assert reg.get("pt_serving_evictions_total").value(
            reason="budget") == 3
        # all slots freed by the end of the run
        assert reg.get("pt_serving_slot_occupancy").value() == 0
        assert reg.get("pt_serving_chunks_total").value() \
            == eng.stats["chunks"]
        assert reg.get("pt_serving_useful_tokens_per_sec").value() > 0


class TestOtherLayers:
    def test_store_ops_latency_and_retries(self):
        from paddle_tpu.distributed.store import TCPStore
        store = TCPStore(is_master=True, use_native=False)
        try:
            store.set("k", b"v")
            assert store.get("k") == b"v"
            store.add("c", 2)
            store.wait("k")
        finally:
            store.close()
        reg = obs.get_registry()
        for op in ("set", "get", "add", "wait"):
            assert reg.get("pt_store_ops_total").value(op=op) == 1
            assert reg.get("pt_store_op_latency_ms").count(op=op) == 1

    def test_store_retries_counted_under_failpoint(self):
        from paddle_tpu.distributed.store import TCPStore
        store = TCPStore(is_master=True, use_native=False, timeout=10.0)
        try:
            failpoints.set_failpoint("store.io", "error*2")
            store.set("k2", b"v")      # retried inside the envelope
        finally:
            failpoints.clear()
            store.close()
        assert obs.get_registry().get(
            "pt_store_retries_total").value() >= 2

    def test_collective_world1_calls_bytes_barrier_latency(self):
        import paddle_tpu.distributed as dist
        t = paddle.to_tensor(np.ones((4, 4), "float32"))
        dist.all_reduce(t)
        dist.barrier()
        reg = obs.get_registry()
        assert reg.get("pt_collective_calls_total").value(
            op="all_reduce") == 1
        assert reg.get("pt_collective_bytes_total").value(
            op="all_reduce") == 64
        assert reg.get("pt_collective_latency_ms").count(
            op="barrier") == 1

    def test_dataloader_threaded_wait_and_depth(self):
        class DS(paddle.io.Dataset):
            def __getitem__(self, i):
                return np.full((3,), i, "float32")

            def __len__(self):
                return 8
        loader = paddle.io.DataLoader(DS(), batch_size=2, num_workers=0)
        # threaded fallback path is taken by the generic queue path;
        # force it by using num_workers=1 iterable-free map dataset
        loader.num_workers = 1
        loader.batch_sampler = paddle.io.BatchSampler(
            DS(), batch_size=2, shuffle=False)
        # monkeypatch-free: exercise the simple threaded-queue path
        from paddle_tpu.io.worker import MultiProcessIter  # noqa: F401
        batches = list(loader._iter_batches())
        assert len(batches) == 4
        # the worker/threaded instrumented paths are covered by the
        # fork'd loader when available; assert the metrics exist and
        # record through a real threaded iteration
        n = sum(1 for _ in paddle.io.DataLoader(DS(), batch_size=2,
                                                num_workers=2))
        assert n == 4
        reg = obs.get_registry()
        assert reg.get("pt_dataloader_wait_ms").count() >= 4

    def test_checkpoint_save_load_bytes_and_fallbacks(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt
        root = str(tmp_path / "root")
        ckpt.save_checkpoint({"a": jnp.ones((8, 8))}, root, 1)
        ckpt.save_checkpoint({"a": jnp.ones((8, 8)) * 2}, root, 2)
        import glob
        (shard,) = glob.glob(os.path.join(root, "step_00000002",
                                          "a", "*.npy"))
        with open(shard, "wb") as f:
            f.write(b"garbage")        # corrupt the newest commit
        out = ckpt.load_state_dict(root)
        assert float(np.asarray(out["a"])[0, 0]) == 1.0  # fell back
        reg = obs.get_registry()
        assert reg.get("pt_checkpoint_save_ms").count() == 2
        assert reg.get("pt_checkpoint_load_ms").count() == 1
        assert reg.get("pt_checkpoint_bytes_total").value(
            direction="save") == 2 * 8 * 8 * 4
        assert reg.get("pt_checkpoint_bytes_total").value(
            direction="load") == 8 * 8 * 4
        assert reg.get("pt_checkpoint_fallbacks_total").value(
            kind="corrupt") == 1


# -- THE overhead contract -------------------------------------------------

class TestZeroSyncContract:
    def test_fit_same_host_sync_count_with_telemetry_on_vs_off(self):
        """The guardian ``_host_bool`` counting shim: a guarded fit
        performs exactly one sync per step, telemetry on or off."""
        cfg = dict(skip_limit=10, ckpt_root=None, loss_spike=False)

        def syncs_of(enabled):
            model = _reg_model(seed=7)
            if not enabled:
                ctx = obs.disabled()
            else:
                from contextlib import nullcontext
                ctx = nullcontext()
            before = guardian.host_sync_count()
            with ctx:
                model.fit(_batches(4), epochs=1, verbose=0,
                          guardian=guardian.GuardianConfig(**cfg))
            return guardian.host_sync_count() - before

        on, off = syncs_of(True), syncs_of(False)
        assert on == off == 4       # one verdict readback per step

    def test_serving_same_device_get_count_with_telemetry_on_vs_off(
            self, gpt, monkeypatch):
        """The serving contract: ONE bundled device_get per engine
        cycle — instrumentation must not add transfers."""
        counts = {"n": 0}
        real = jax.device_get

        def counting(x):
            counts["n"] += 1
            return real(x)

        def run_once(enabled):
            rng = np.random.RandomState(5)
            eng = ServingEngine(gpt, num_slots=2, chunk=4,
                                prefill_buckets=(8,))
            for b in (3, 5, 4):
                eng.submit(rng.randint(0, 1024, (6,)).astype("int32"), b)
            counts["n"] = 0
            monkeypatch.setattr(jax, "device_get", counting)
            try:
                if enabled:
                    eng.run()
                else:
                    with obs.disabled():
                        eng.run()
            finally:
                monkeypatch.setattr(jax, "device_get", real)
            return counts["n"], eng.stats["chunks"]

        (n_on, chunks_on) = run_once(True)
        (n_off, chunks_off) = run_once(False)
        assert chunks_on == chunks_off
        assert n_on == n_off        # zero additional transfers
        assert n_on > 0             # the shim actually measured syncs


# -- one run, one timeline -------------------------------------------------

class TestTimeline:
    def test_merged_trace_three_streams_shared_clock(self, tmp_path,
                                                     monkeypatch):
        """Acceptance: instrumented fit + serving session -> merged
        chrome trace holding host spans (X), guardian events (i) and
        metric samples (C) with overlapping timestamp ranges."""
        import paddle_tpu.profiler as profiler
        monkeypatch.setattr(profiler, "_native_tracer", lambda: None)
        profiler._HOST_EVENTS.clear()
        profiler._COLLECTING[0] = True
        try:
            obs.start_capture()
            with profiler.RecordEvent("fit_session"):
                model = _reg_model()
                model.fit(_batches(3), epochs=1, verbose=0,
                          guardian=guardian.GuardianConfig(
                              skip_limit=10, ckpt_root=None,
                              loss_spike=False))
            guardian.emit("skip_step", step=99, reason="nonfinite",
                          consecutive=1)   # a guardian instant for sure
            obs.stop_capture()
            path = timeline.export_chrome_trace(
                str(tmp_path / "run.trace.json"))
        finally:
            profiler._COLLECTING[0] = False
            profiler._HOST_EVENTS.clear()
        events = json.load(open(path))["traceEvents"]
        spans = [e for e in events if e["ph"] == "X"]
        instants = [e for e in events if e["ph"] == "i"]
        samples = [e for e in events if e["ph"] == "C"]
        assert spans and instants and samples
        assert any(e["name"] == "fit_session" for e in spans)
        assert any(e["name"] == "skip_step" for e in instants)
        assert any(e["name"].startswith("pt_train_") for e in samples)
        # shared clock: every stream's timestamps land inside (a small
        # margin around) the outer fit span
        (span,) = [e for e in spans if e["name"] == "fit_session"]
        lo, hi = span["ts"] - 1e6, span["ts"] + span["dur"] + 1e6
        for e in instants + samples:
            assert lo <= e["ts"] <= hi

    def test_profiler_loads_merged_trace_as_span_subset(self, tmp_path,
                                                        monkeypatch):
        import paddle_tpu.profiler as profiler
        monkeypatch.setattr(profiler, "_native_tracer", lambda: None)
        profiler._HOST_EVENTS.clear()
        profiler._COLLECTING[0] = True
        try:
            obs.start_capture()
            with profiler.RecordEvent("only_span"):
                obs.inc("pt_train_tokens_total", 1)
            obs.stop_capture()
            path = timeline.export_chrome_trace(
                str(tmp_path / "t.json"))
        finally:
            profiler._COLLECTING[0] = False
            profiler._HOST_EVENTS.clear()
        res = profiler.load_profiler_result(path)
        assert [e.name for e in res] == ["only_span"]


# -- report CLI ------------------------------------------------------------

class TestReportCLI:
    def test_report_renders_prom_jsonl_trace(self, tmp_path, capsys):
        obs.start_capture()
        obs.inc("pt_serving_admissions_total", 4)
        for v in (3.0, 9.0, 27.0):
            obs.observe("pt_serving_ttft_ms", v)
        obs.stop_capture()
        prom = export.write_prometheus(str(tmp_path / "r.prom"))
        jsl = export.write_jsonl(str(tmp_path / "r.jsonl"), run="t")
        tr = timeline.export_chrome_trace(
            str(tmp_path / "r.trace.json"), include_profiler=False,
            include_guardian=False)
        rc = obs.main(["report", "--prom", prom, "--jsonl", jsl,
                       "--trace", tr])
        out = capsys.readouterr().out
        assert rc == 0
        assert "pt_serving_admissions_total  4" in out
        assert "pt_serving_ttft_ms" in out and "count=3" in out
        assert "p50" in out and "counter samples" in out

    def test_report_without_sinks_exits_2(self, capsys):
        assert obs.main(["report"]) == 2

    def test_quantile_interpolates_inside_winning_bucket(self):
        from paddle_tpu.observability.report import _quantile
        # cumulative: 50 obs <= 100, 100 obs <= 1000.  q=0.6 -> the
        # 60th obs sits 10/50 into the (100, 1000] bucket.
        buckets = [("100", 50), ("1000", 100), ("+Inf", 100)]
        val, exact = _quantile(buckets, 0.6)
        assert exact and val == pytest.approx(280.0)
        # first-bucket targets interpolate from (0, 0)
        val, exact = _quantile(buckets, 0.25)
        assert exact and val == pytest.approx(50.0)


# -- the lint pass ---------------------------------------------------------

class TestMetricsRegistryLint:
    def test_unknown_metric_reference_is_a_finding(self, tmp_path):
        from paddle_tpu.analysis.runner import run_passes
        bogus = "pt_serving_" + "imaginary_gauge"
        (tmp_path / "test_fixture.py").write_text(
            f'REF = "{bogus}"\n'
            'IGNORED = "pt_batch_shm_tag"\n')
        found = run_passes(paths=[str(tmp_path)],
                           passes=["metrics-registry"])
        assert [(f.code, f.detail) for f in found] == \
            [("unknown-metric", bogus)]

    def test_doc_table_drift_is_a_finding(self, monkeypatch):
        from paddle_tpu.analysis.runner import run_passes, REPO_ROOT
        from paddle_tpu.observability import catalog as cat
        drifted = "pt_train_" + "zz_drifted"
        monkeypatch.setitem(cat.METRICS, drifted, {"type": "gauge",
                                                   "labels": ()})
        found = run_passes(paths=[os.path.join(REPO_ROOT, "docs")],
                           passes=["metrics-registry"])
        assert [(f.code, f.detail) for f in found] == \
            [("catalog-drift", drifted)]

    def test_real_tree_is_clean(self):
        from paddle_tpu.analysis.runner import run_passes
        assert run_passes(passes=["metrics-registry"]) == []
