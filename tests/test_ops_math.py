"""numpy-golden op tests (math/linalg/reduction) via the OpTest harness."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest

rng = np.random.RandomState(42)


class TestElementwise(OpTest):
    def test_add(self):
        a, b = rng.rand(3, 4).astype("f4"), rng.rand(3, 4).astype("f4")
        self.check_output(paddle.add, [a, b], a + b)
        self.check_grad(paddle.add, [a, b])

    def test_subtract(self):
        a, b = rng.rand(3, 4).astype("f4"), rng.rand(4).astype("f4")
        self.check_output(paddle.subtract, [a, b], a - b)
        self.check_grad(paddle.subtract, [a, b])

    def test_multiply_broadcast(self):
        a, b = rng.rand(2, 3, 4).astype("f4"), rng.rand(3, 1).astype("f4")
        self.check_output(paddle.multiply, [a, b], a * b)
        self.check_grad(paddle.multiply, [a, b])

    def test_divide(self):
        a = rng.rand(3, 4).astype("f4") + 0.5
        b = rng.rand(3, 4).astype("f4") + 0.5
        self.check_output(paddle.divide, [a, b], a / b)
        self.check_grad(paddle.divide, [a, b])

    def test_pow(self):
        a = rng.rand(3, 4).astype("f4") + 0.5
        self.check_output(paddle.pow, [a], a ** 2.5, y=2.5)
        self.check_grad(paddle.pow, [a], y=2.5)

    def test_maximum_minimum(self):
        a, b = rng.randn(3, 4).astype("f4"), rng.randn(3, 4).astype("f4")
        self.check_output(paddle.maximum, [a, b], np.maximum(a, b))
        self.check_output(paddle.minimum, [a, b], np.minimum(a, b))

    def test_unary_suite(self):
        x = (rng.rand(3, 4).astype("f4") + 0.1)
        for op, ref in [
            (paddle.exp, np.exp), (paddle.log, np.log),
            (paddle.sqrt, np.sqrt), (paddle.rsqrt, lambda v: 1/np.sqrt(v)),
            (paddle.sin, np.sin), (paddle.cos, np.cos),
            (paddle.tanh, np.tanh), (paddle.abs, np.abs),
            (paddle.floor, np.floor), (paddle.ceil, np.ceil),
            (paddle.round, np.round), (paddle.square, np.square),
            (paddle.sigmoid, lambda v: 1/(1+np.exp(-v))),
            (paddle.reciprocal, lambda v: 1/v),
            (paddle.erf, None), (paddle.expm1, np.expm1),
            (paddle.log1p, np.log1p), (paddle.log2, np.log2),
            (paddle.log10, np.log10),
        ]:
            if ref is None:
                continue
            self.check_output(op, [x], ref(x), rtol=2e-4, atol=1e-4)
        # differentiable subset grad check on tiny input
        t = rng.rand(2, 2).astype("f4") + 0.3
        for op in [paddle.exp, paddle.log, paddle.sqrt, paddle.tanh,
                   paddle.sigmoid, paddle.square]:
            self.check_grad(op, [t])

    def test_clip(self):
        x = rng.randn(3, 4).astype("f4")
        self.check_output(paddle.clip, [x], np.clip(x, -0.5, 0.5),
                          min=-0.5, max=0.5)

    def test_floor_divide_mod(self):
        a = rng.randint(1, 20, (3, 4)).astype("f4")
        b = rng.randint(1, 5, (3, 4)).astype("f4")
        self.check_output(paddle.floor_divide, [a, b], np.floor_divide(a, b))
        self.check_output(paddle.mod, [a, b], np.mod(a, b))


class TestReductions(OpTest):
    def test_sum_mean(self):
        x = rng.rand(3, 4, 5).astype("f4")
        self.check_output(paddle.sum, [x], x.sum())
        self.check_output(paddle.sum, [x], x.sum(1), axis=1)
        self.check_output(paddle.sum, [x], x.sum(axis=(0, 2), keepdims=True),
                          axis=[0, 2], keepdim=True)
        self.check_output(paddle.mean, [x], x.mean(2), axis=2)
        self.check_grad(paddle.mean, [x[:2, :2, :2]], axis=1)

    def test_max_min_prod(self):
        x = rng.rand(3, 4).astype("f4")
        self.check_output(paddle.max, [x], x.max(1), axis=1)
        self.check_output(paddle.min, [x], x.min(0), axis=0)
        self.check_output(paddle.prod, [x], x.prod(1), axis=1)

    def test_logsumexp(self):
        x = rng.randn(3, 4).astype("f4")
        ref = np.log(np.exp(x).sum(axis=1))
        self.check_output(paddle.logsumexp, [x], ref, axis=1,
                          rtol=1e-4, atol=1e-5)

    def test_cumsum_cumprod(self):
        x = rng.rand(3, 4).astype("f4")
        self.check_output(paddle.cumsum, [x], np.cumsum(x, 1), axis=1)
        self.check_output(paddle.cumprod, [x], np.cumprod(x, 1), dim=1)

    def test_norms(self):
        x = rng.randn(3, 4).astype("f4")
        self.check_output(paddle.norm, [x], np.linalg.norm(x))
        self.check_output(paddle.norm, [x], np.linalg.norm(x, axis=1), axis=1)
        self.check_output(paddle.norm, [x], np.abs(x).sum(1), p=1, axis=1)

    def test_all_any(self):
        x = rng.rand(3, 4) > 0.5
        self.check_output(paddle.all, [x], x.all(1), axis=1)
        self.check_output(paddle.any, [x], x.any(0), axis=0)


class TestLinalg(OpTest):
    def test_matmul(self):
        a = rng.rand(3, 4).astype("f4")
        b = rng.rand(4, 5).astype("f4")
        self.check_output(paddle.matmul, [a, b], a @ b, rtol=1e-4)
        self.check_grad(paddle.matmul, [a[:2, :2], b[:2, :2]])

    def test_matmul_batched_transpose(self):
        a = rng.rand(2, 3, 4).astype("f4")
        b = rng.rand(2, 5, 4).astype("f4")
        ref = a @ b.transpose(0, 2, 1)
        self.check_output(paddle.matmul, [a, b], ref, transpose_y=True,
                          rtol=1e-4)

    def test_dot_t_mv(self):
        a, b = rng.rand(5).astype("f4"), rng.rand(5).astype("f4")
        self.check_output(paddle.dot, [a, b], a.dot(b), rtol=1e-4)
        m = rng.rand(3, 4).astype("f4")
        self.check_output(paddle.t, [m], m.T)
        v = rng.rand(4).astype("f4")
        self.check_output(paddle.mv, [m, v], m @ v, rtol=1e-4)

    def test_bmm(self):
        a = rng.rand(2, 3, 4).astype("f4")
        b = rng.rand(2, 4, 5).astype("f4")
        self.check_output(paddle.bmm, [a, b], a @ b, rtol=1e-4)

    def test_einsum(self):
        a = rng.rand(2, 3).astype("f4")
        b = rng.rand(3, 4).astype("f4")
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4, atol=1e-5)

    def test_solve_inv(self):
        a = rng.rand(3, 3).astype("f4") + 3 * np.eye(3, dtype="f4")
        b = rng.rand(3, 2).astype("f4")
        self.check_output(paddle.linalg.solve, [a, b],
                          np.linalg.solve(a, b), rtol=1e-3, atol=1e-4)
        self.check_output(paddle.linalg.inv, [a], np.linalg.inv(a),
                          rtol=1e-3, atol=1e-4)

    def test_svd_qr_cholesky(self):
        a = rng.rand(4, 3).astype("f4")
        u, s, vh = np.linalg.svd(a, full_matrices=False)
        _, ps, _ = paddle.linalg.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(ps.numpy(), s, rtol=1e-3, atol=1e-4)
        spd = a.T @ a + np.eye(3, dtype="f4")
        c = paddle.linalg.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(c.numpy() @ c.numpy().T, spd,
                                   rtol=1e-3, atol=1e-4)


class TestComparisonLogic(OpTest):
    def test_compare(self):
        a = rng.randn(3, 4).astype("f4")
        b = rng.randn(3, 4).astype("f4")
        self.check_output(paddle.equal, [a, a], np.equal(a, a))
        self.check_output(paddle.greater_than, [a, b], a > b)
        self.check_output(paddle.less_equal, [a, b], a <= b)
        self.check_output(paddle.not_equal, [a, b], a != b)

    def test_logical(self):
        a = rng.rand(3, 4) > 0.5
        b = rng.rand(3, 4) > 0.5
        self.check_output(paddle.logical_and, [a, b], a & b)
        self.check_output(paddle.logical_or, [a, b], a | b)
        self.check_output(paddle.logical_not, [a], ~a)
        self.check_output(paddle.logical_xor, [a, b], a ^ b)

    def test_isnan_isinf(self):
        x = np.array([1.0, np.nan, np.inf, -np.inf], dtype="f4")
        self.check_output(paddle.isnan, [x], np.isnan(x))
        self.check_output(paddle.isinf, [x], np.isinf(x))
        self.check_output(paddle.isfinite, [x], np.isfinite(x))


class TestSearchSort(OpTest):
    def test_argmax_argmin(self):
        x = rng.randn(3, 4).astype("f4")
        self.check_output(paddle.argmax, [x], x.argmax(1), axis=1)
        self.check_output(paddle.argmin, [x], x.argmin(0), axis=0)

    def test_sort_argsort(self):
        x = rng.randn(3, 4).astype("f4")
        self.check_output(paddle.sort, [x], np.sort(x, 1), axis=1)
        self.check_output(paddle.argsort, [x], np.argsort(x, 1, kind="stable"),
                          axis=1)

    def test_topk(self):
        x = rng.randn(3, 5).astype("f4")
        v = paddle.topk(paddle.to_tensor(x), k=2, axis=1)[0]
        np.testing.assert_allclose(v.numpy(), np.sort(x, 1)[:, ::-1][:, :2])

    def test_where_masked_select(self):
        x = rng.randn(3, 4).astype("f4")
        y = rng.randn(3, 4).astype("f4")
        cond = x > 0
        self.check_output(paddle.where, [cond, x, y], np.where(cond, x, y))

    def test_nonzero_unique(self):
        x = np.array([0, 3, 0, 4], dtype="f4")
        nz = paddle.nonzero(paddle.to_tensor(x))
        np.testing.assert_allclose(nz.numpy(), [[1], [3]])
        u = paddle.unique(paddle.to_tensor(np.array([3, 1, 3, 2])))
        np.testing.assert_allclose(u.numpy(), [1, 2, 3])

    def test_gather_scatter_index_select(self):
        x = rng.rand(5, 3).astype("f4")
        idx = np.array([0, 2, 4])
        self.check_output(paddle.gather, [x], x[idx],
                          index=paddle.to_tensor(idx), axis=0)
        self.check_output(paddle.index_select, [x], x[:, [0, 2]],
                          index=paddle.to_tensor(np.array([0, 2])), axis=1)


def test_new_math_ops_r3():
    """logcumsumexp / trapezoid / renorm / frexp / vander (reference:
    paddle.* op surface)."""
    x = paddle.to_tensor(np.asarray([[1.0, 2.0], [3.0, 4.0]], "f4"))
    lcse = paddle.logcumsumexp(x, axis=1).numpy()
    ref = np.log(np.cumsum(np.exp(x.numpy()), axis=1))
    np.testing.assert_allclose(lcse, ref, rtol=1e-5)
    t = float(paddle.trapezoid(paddle.to_tensor(
        np.asarray([1.0, 2.0, 3.0], "f4"))))
    assert t == pytest.approx(4.0)
    rn = paddle.renorm(x, p=2.0, axis=0, max_norm=1.0).numpy()
    np.testing.assert_allclose(np.linalg.norm(rn, axis=1), [1.0, 1.0],
                               rtol=1e-5)
    m, e = paddle.frexp(paddle.to_tensor(np.asarray([8.0, 0.5], "f4")))
    np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), [8.0, 0.5])
    v = paddle.vander(paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], "f4")),
                      n=3).numpy()
    np.testing.assert_allclose(v, np.vander([1.0, 2.0, 3.0], 3))


def test_new_linalg_ops_r3():
    """linalg.cond / lu / householder_product."""
    import scipy.linalg as sl
    a = np.asarray([[1.0, 2.0], [3.0, 4.0]], "f4")
    c = float(paddle.linalg.cond(paddle.to_tensor(a)))
    assert c == pytest.approx(np.linalg.cond(a), rel=1e-4)
    lu_m, piv = paddle.linalg.lu(paddle.to_tensor(a))
    ref_lu, ref_piv = sl.lu_factor(a)
    np.testing.assert_allclose(lu_m.numpy(), ref_lu, rtol=1e-5)
    np.testing.assert_allclose(piv.numpy(), ref_piv + 1)
    (h, tau), _r = sl.qr(np.random.RandomState(0).randn(4, 3),
                         mode="raw")
    q = paddle.linalg.householder_product(
        paddle.to_tensor(np.asarray(h, "f4").copy()),
        paddle.to_tensor(np.asarray(tau, "f4").copy()))
    assert tuple(q.shape) == (4, 3)
    # golden: Q reconstructed by scipy's orgqr from the same reflectors
    ref_q = sl.lapack.sorgqr(np.asarray(h, "f4"), np.asarray(tau, "f4"))[0]
    np.testing.assert_allclose(q.numpy(), ref_q[:, :3], rtol=1e-4,
                               atol=1e-5)
