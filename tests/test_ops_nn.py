"""numpy-golden op tests for nn.functional (activation/loss/norm/conv/pool)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest

rng = np.random.RandomState(7)


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


class TestActivations(OpTest):
    def test_relu_family(self):
        x = rng.randn(3, 4).astype("f4")
        self.check_output(F.relu, [x], np.maximum(x, 0))
        self.check_output(F.relu6, [x * 4], np.clip(x * 4, 0, 6))
        self.check_output(F.leaky_relu, [x], np.where(x > 0, x, 0.01 * x))
        self.check_output(F.elu, [x], np.where(x > 0, x, np.exp(x) - 1),
                          rtol=1e-4)
        self.check_output(F.hardtanh, [x], np.clip(x, -1, 1))
        self.check_grad(F.relu, [rng.rand(2, 2).astype("f4") + 0.1])

    def test_gelu(self):
        x = rng.randn(3, 4).astype("f4")
        from scipy.special import erf as serf
        ref = 0.5 * x * (1 + serf(x / np.sqrt(2)))
        self.check_output(F.gelu, [x], ref, rtol=1e-3, atol=1e-4)
        tanh_ref = 0.5 * x * (1 + np.tanh(
            np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3)))
        self.check_output(F.gelu, [x], tanh_ref, approximate=True,
                          rtol=1e-3, atol=1e-4)

    def test_softmax_logsoftmax(self):
        x = rng.randn(3, 5).astype("f4")
        self.check_output(F.softmax, [x], _softmax_np(x), rtol=1e-5)
        self.check_output(F.log_softmax, [x], np.log(_softmax_np(x)),
                          rtol=1e-4, atol=1e-5)
        self.check_output(F.softmax, [x], _softmax_np(x, 0), axis=0)
        self.check_grad(F.softmax, [rng.randn(2, 3).astype("f4")])

    def test_misc_acts(self):
        x = rng.randn(3, 4).astype("f4")
        self.check_output(F.silu, [x], x / (1 + np.exp(-x)), rtol=1e-4)
        self.check_output(F.mish, [x],
                          x * np.tanh(np.log1p(np.exp(x))), rtol=1e-4)
        self.check_output(F.softplus, [x], np.log1p(np.exp(x)), rtol=1e-4)
        self.check_output(F.hardswish, [x],
                          x * np.clip(x + 3, 0, 6) / 6, rtol=1e-4)
        self.check_output(F.hardsigmoid, [x],
                          np.clip(x / 6 + 0.5, 0, 1), rtol=1e-4)
        self.check_output(F.swish, [x], x / (1 + np.exp(-x)), rtol=1e-4)
        self.check_output(F.tanhshrink, [x], x - np.tanh(x), rtol=1e-4,
                          atol=1e-5)


class TestLosses(OpTest):
    def test_mse_l1(self):
        x = rng.randn(4, 3).astype("f4")
        y = rng.randn(4, 3).astype("f4")
        self.check_output(F.mse_loss, [x, y], ((x - y) ** 2).mean())
        self.check_output(F.l1_loss, [x, y], np.abs(x - y).mean())
        self.check_output(F.mse_loss, [x, y], (x - y) ** 2,
                          reduction="none")
        self.check_grad(F.mse_loss, [x[:2, :2], y[:2, :2]], grad_inputs=[0])

    def test_cross_entropy(self):
        logits = rng.randn(4, 5).astype("f4")
        labels = rng.randint(0, 5, (4,)).astype("i8")
        p = _softmax_np(logits)
        ref = -np.log(p[np.arange(4), labels]).mean()
        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels))
        np.testing.assert_allclose(float(out), ref, rtol=1e-4)
        # soft labels
        soft = _softmax_np(rng.randn(4, 5).astype("f4"))
        ref2 = -(soft * np.log(p)).sum(1).mean()
        out2 = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(soft), soft_label=True)
        np.testing.assert_allclose(float(out2), ref2, rtol=1e-4)

    def test_nll_bce(self):
        logp = np.log(_softmax_np(rng.randn(4, 5).astype("f4")))
        labels = rng.randint(0, 5, (4,)).astype("i8")
        ref = -logp[np.arange(4), labels].mean()
        out = F.nll_loss(paddle.to_tensor(logp), paddle.to_tensor(labels))
        np.testing.assert_allclose(float(out), ref, rtol=1e-4)

        x = rng.rand(4, 3).astype("f4") * 0.8 + 0.1
        y = (rng.rand(4, 3) > 0.5).astype("f4")
        ref = -(y * np.log(x) + (1 - y) * np.log(1 - x)).mean()
        self.check_output(F.binary_cross_entropy, [x, y], ref, rtol=1e-5)

        logits = rng.randn(4, 3).astype("f4")
        sp = 1 / (1 + np.exp(-logits))
        refl = -(y * np.log(sp) + (1 - y) * np.log(1 - sp)).mean()
        self.check_output(F.binary_cross_entropy_with_logits, [logits, y],
                          refl, rtol=1e-4)

    def test_smooth_l1_kldiv(self):
        x = rng.randn(4, 3).astype("f4")
        y = rng.randn(4, 3).astype("f4")
        d = x - y
        ref = np.where(np.abs(d) < 1.0, 0.5 * d * d, np.abs(d) - 0.5).mean()
        self.check_output(F.smooth_l1_loss, [x, y], ref, rtol=1e-5)

        logp = np.log(_softmax_np(x))
        q = _softmax_np(y)
        ref_kl = (q * (np.log(q) - logp)).mean()
        self.check_output(F.kl_div, [logp, q], ref_kl, rtol=1e-4)


class TestNorms(OpTest):
    def test_layer_norm(self):
        x = rng.randn(2, 3, 8).astype("f4")
        w = rng.rand(8).astype("f4")
        b = rng.rand(8).astype("f4")
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(var + 1e-5) * w + b
        out = F.layer_norm(paddle.to_tensor(x), normalized_shape=[8],
                           weight=paddle.to_tensor(w),
                           bias=paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_rms_norm(self):
        x = rng.randn(2, 8).astype("f4")
        w = rng.rand(8).astype("f4")
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
        out = F.rms_norm(paddle.to_tensor(x), paddle.to_tensor(w),
                         epsilon=1e-6)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_batch_norm_infer(self):
        x = rng.randn(4, 3, 5, 5).astype("f4")
        rm = rng.rand(3).astype("f4")
        rv = rng.rand(3).astype("f4") + 0.5
        w = rng.rand(3).astype("f4")
        b = rng.rand(3).astype("f4")
        ref = ((x - rm[:, None, None]) / np.sqrt(rv[:, None, None] + 1e-5)
               * w[:, None, None] + b[:, None, None])
        out = F.batch_norm(paddle.to_tensor(x), paddle.to_tensor(rm),
                           paddle.to_tensor(rv), weight=paddle.to_tensor(w),
                           bias=paddle.to_tensor(b), training=False)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


class TestConvPool(OpTest):
    def test_conv2d_golden(self):
        # golden via scipy correlate on a tiny case
        x = rng.randn(1, 1, 5, 5).astype("f4")
        w = rng.randn(2, 1, 3, 3).astype("f4")
        from scipy.signal import correlate2d
        ref = np.stack([correlate2d(x[0, 0], w[o, 0], mode="valid")
                        for o in range(2)])[None]
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_conv2d_stride_pad_group_dilation(self):
        x = rng.randn(2, 4, 9, 9).astype("f4")
        w = rng.randn(6, 2, 3, 3).astype("f4")
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), stride=2,
                       padding=1, groups=2)
        assert out.shape == [2, 6, 5, 5]
        out2 = F.conv2d(paddle.to_tensor(x),
                        paddle.to_tensor(rng.randn(6, 4, 3, 3).astype("f4")),
                        dilation=2)
        assert out2.shape == [2, 6, 5, 5]

    def test_conv_grad(self):
        x = rng.randn(1, 1, 4, 4).astype("f4")
        w = rng.randn(1, 1, 2, 2).astype("f4")
        self.check_grad(lambda a, b: F.conv2d(a, b), [x, w], rtol=2e-2,
                        atol=1e-2)

    def test_pools(self):
        x = rng.randn(1, 2, 4, 4).astype("f4")
        ref = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
        out = F.max_pool2d(paddle.to_tensor(x), kernel_size=2, stride=2)
        np.testing.assert_allclose(out.numpy(), ref)
        ref_avg = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
        out = F.avg_pool2d(paddle.to_tensor(x), kernel_size=2, stride=2)
        np.testing.assert_allclose(out.numpy(), ref_avg, rtol=5e-6)
        out = F.adaptive_avg_pool2d(paddle.to_tensor(x), output_size=1)
        np.testing.assert_allclose(out.numpy(),
                                   x.mean(axis=(2, 3), keepdims=True),
                                   rtol=5e-6)

    def test_embedding_linear(self):
        table = rng.randn(10, 4).astype("f4")
        ids = np.array([[1, 3], [5, 9]])
        out = F.embedding(paddle.to_tensor(ids), paddle.to_tensor(table))
        np.testing.assert_allclose(out.numpy(), table[ids])
        x = rng.randn(3, 4).astype("f4")
        wt = rng.randn(4, 5).astype("f4")
        b = rng.randn(5).astype("f4")
        out = F.linear(paddle.to_tensor(x), paddle.to_tensor(wt),
                       paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), x @ wt + b, rtol=1e-4,
                                   atol=1e-5)

    def test_dropout_train_eval(self):
        x = np.ones((100, 100), dtype="f4")
        out = F.dropout(paddle.to_tensor(x), p=0.5, training=False)
        np.testing.assert_allclose(out.numpy(), x)
        out = F.dropout(paddle.to_tensor(x), p=0.5, training=True)
        kept = out.numpy() != 0
        assert 0.3 < kept.mean() < 0.7
        # upscale_in_train: kept values are x/(1-p)
        vals = out.numpy()[kept]
        np.testing.assert_allclose(vals, 2.0, rtol=1e-5)

    def test_pad_interpolate(self):
        x = rng.randn(1, 1, 3, 3).astype("f4")
        out = F.pad(paddle.to_tensor(x), [1, 1, 1, 1])
        assert out.shape == [1, 1, 5, 5]
        np.testing.assert_allclose(out.numpy()[0, 0, 1:4, 1:4], x[0, 0])
        up = F.interpolate(paddle.to_tensor(x), scale_factor=2,
                           mode="nearest")
        assert up.shape == [1, 1, 6, 6]
        np.testing.assert_allclose(up.numpy()[0, 0, ::2, ::2], x[0, 0])


class TestGridSample:
    """grid_sample / affine_grid / temporal_shift (reference:
    paddle.nn.functional; goldens from torch, like the signal suite)."""

    def test_grid_sample_torch_golden(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as TF
        r = np.random.RandomState(0)
        x = r.randn(2, 3, 8, 8).astype("f4")
        # range [-2, 2): out-of-bounds coords exercise every padding mode
        grid = (r.rand(2, 5, 6, 2).astype("f4") * 4 - 2)
        for mode in ("bilinear", "nearest"):
            for pad in ("zeros", "border", "reflection"):
                for ac in (True, False):
                    ref = TF.grid_sample(torch.tensor(x),
                                         torch.tensor(grid), mode=mode,
                                         padding_mode=pad,
                                         align_corners=ac).numpy()
                    got = F.grid_sample(paddle.to_tensor(x),
                                        paddle.to_tensor(grid), mode=mode,
                                        padding_mode=pad,
                                        align_corners=ac).numpy()
                    np.testing.assert_allclose(got, ref, rtol=1e-5,
                                               atol=1e-5,
                                               err_msg=f"{mode}/{pad}/ac={ac}")

    def test_grid_sample_grads_flow(self):
        r = np.random.RandomState(1)
        x = paddle.to_tensor(r.randn(1, 2, 4, 4).astype("f4"),
                             stop_gradient=False)
        g = paddle.to_tensor((r.rand(1, 3, 3, 2).astype("f4") - 0.5),
                             stop_gradient=False)
        F.grid_sample(x, g).sum().backward()
        assert x.grad is not None and g.grad is not None
        assert np.isfinite(x.grad.numpy()).all()

    def test_affine_grid_torch_golden(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as TF
        r = np.random.RandomState(2)
        theta = r.randn(2, 2, 3).astype("f4")
        for ac in (True, False):
            ref = TF.affine_grid(torch.tensor(theta), (2, 3, 5, 7),
                                 align_corners=ac).numpy()
            got = F.affine_grid(paddle.to_tensor(theta), [2, 3, 5, 7],
                                align_corners=ac).numpy()
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_temporal_shift(self):
        r = np.random.RandomState(3)
        x = r.randn(4, 8, 2, 2).astype("f4")      # N=2 segments of T=2
        out = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                               shift_ratio=0.25).numpy()
        v5 = x.reshape(2, 2, 8, 2, 2)
        # first quarter shifted backward (t+1), second quarter forward
        np.testing.assert_allclose(out.reshape(2, 2, 8, 2, 2)[:, 0, :2],
                                   v5[:, 1, :2])
        np.testing.assert_allclose(out.reshape(2, 2, 8, 2, 2)[:, 1, 2:4],
                                   v5[:, 0, 2:4])
        np.testing.assert_allclose(out.reshape(2, 2, 8, 2, 2)[:, :, 4:],
                                   v5[:, :, 4:])
