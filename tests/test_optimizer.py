import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import SGD, Adam, AdamW, Momentum
from paddle_tpu.optimizer.lr import CosineAnnealingDecay, LinearWarmup


def _quad_problem(opt_cls, lr=0.1, steps=50, **kw):
    w = paddle.to_tensor([5.0, -3.0], stop_gradient=False)
    opt = opt_cls(learning_rate=lr, parameters=[w], **kw)
    for _ in range(steps):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w.numpy()


def test_sgd_converges():
    w = _quad_problem(SGD, lr=0.1, steps=40)
    np.testing.assert_allclose(w, 0, atol=1e-2)


def test_momentum_converges():
    w = _quad_problem(Momentum, lr=0.02, steps=60)
    np.testing.assert_allclose(w, 0, atol=0.25)


def test_adam_converges():
    w = _quad_problem(Adam, lr=0.5, steps=60)
    np.testing.assert_allclose(w, 0, atol=0.2)


def test_adam_matches_reference_formula():
    w0 = np.array([1.0], dtype="float32")
    w = paddle.to_tensor(w0.copy(), stop_gradient=False)
    opt = Adam(learning_rate=0.1, parameters=[w])
    (w * 3.0).sum().backward()
    opt.step()
    # one adam step with g=3: m=0.3, v=0.009*... manual
    g = 3.0
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    expect = 1.0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(w.numpy(), [expect], rtol=1e-5)


def test_adamw_decoupled_decay():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    opt = AdamW(learning_rate=0.1, parameters=[w], weight_decay=0.5)
    paddle.sum(w * 0.0).backward()  # zero grad → pure decay + eps-sized adam
    w._grad = paddle.zeros([1])._value
    opt.step()
    # decay factor applies before adam update with zero grads
    np.testing.assert_allclose(w.numpy(), [1.0 * (1 - 0.1 * 0.5)],
                               rtol=1e-5)


def test_grad_clip_global_norm():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    w = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    opt = SGD(learning_rate=1.0, parameters=[w],
              grad_clip=ClipGradByGlobalNorm(1.0))
    (w * w).sum().backward()  # grad = [6, 8], norm 10 → scaled to [0.6,0.8]
    opt.step()
    np.testing.assert_allclose(w.numpy(), [3.0 - 0.6, 4.0 - 0.8],
                               rtol=1e-5)


def test_lr_scheduler():
    sch = CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    w = paddle.to_tensor([1.0], stop_gradient=False)
    opt = SGD(learning_rate=sch, parameters=[w])
    assert abs(opt.get_lr() - 1.0) < 1e-6
    sch.step()
    assert opt.get_lr() < 1.0


def test_linear_warmup():
    sch = LinearWarmup(learning_rate=0.1, warmup_steps=10, start_lr=0.0,
                       end_lr=0.1)
    lrs = []
    for _ in range(12):
        lrs.append(sch())
        sch.step()
    assert lrs[0] == 0.0
    assert abs(lrs[5] - 0.05) < 1e-6
    assert abs(lrs[11] - 0.1) < 1e-6


def test_optimizer_state_dict_roundtrip():
    w = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    opt = Adam(learning_rate=0.1, parameters=[w])
    (w * w).sum().backward()
    opt.step()
    sd = opt.state_dict()
    w2 = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    opt2 = Adam(learning_rate=0.1, parameters=[w2])
    opt2.set_state_dict(sd)
    st = opt2._accumulators[id(w2)]
    np.testing.assert_allclose(np.asarray(st["moment1"]),
                               np.asarray(opt._accumulators[id(w)]
                                          ["moment1"]))


def test_round4_optimizers_converge_quadratic():
    """Rprop/ASGD/NAdam/RAdam (reference: paddle.optimizer round-3
    additions) minimize a convex quadratic; state shapes sane."""
    import numpy as np
    import paddle_tpu as paddle

    target = np.asarray([1.5, -2.0, 0.5, 3.0], "f4")

    def run(opt_cls, steps=120, **kw):
        paddle.seed(0)
        p = paddle.to_tensor(np.zeros(4, "f4"), stop_gradient=False)
        opt = opt_cls(parameters=[p], **kw)
        for _ in range(steps):
            loss = ((p - paddle.to_tensor(target)) ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return np.asarray(p._value)

    got = run(paddle.optimizer.Rprop, learning_rate=0.01)
    np.testing.assert_allclose(got, target, atol=0.05)
    got = run(paddle.optimizer.ASGD, learning_rate=0.05, batch_num=2)
    np.testing.assert_allclose(got, target, atol=0.05)
    got = run(paddle.optimizer.NAdam, learning_rate=0.3)
    np.testing.assert_allclose(got, target, atol=0.1)
    got = run(paddle.optimizer.RAdam, learning_rate=0.3)
    np.testing.assert_allclose(got, target, atol=0.1)
