"""minimize_bfgs / minimize_lbfgs (reference:
test/legacy_test/test_minimize_{bfgs,lbfgs}.py — quadratic + Rosenbrock
convergence, jit-compatibility)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.incubate.optimizer.functional import (minimize_bfgs,
                                                      minimize_lbfgs)


def _quad(x):
    # f(x) = 0.5 x^T A x - b^T x with SPD A; minimum at A^-1 b
    A = jnp.asarray([[3.0, 0.5], [0.5, 1.0]])
    b = jnp.asarray([1.0, -2.0])
    return 0.5 * x @ A @ x - b @ x


_QUAD_MIN = np.linalg.solve(np.asarray([[3.0, 0.5], [0.5, 1.0]]),
                            np.asarray([1.0, -2.0]))


def _rosenbrock(x):
    return (1.0 - x[0]) ** 2 + 100.0 * (x[1] - x[0] ** 2) ** 2


@pytest.mark.parametrize("minimize", [minimize_bfgs, minimize_lbfgs])
def test_quadratic_converges(minimize):
    # tolerance_grad=1e-5: the default 1e-7 sits at f32 machine eps
    out = minimize(_quad, np.asarray([0.0, 0.0], "f4"), max_iters=50,
                   tolerance_grad=1e-5)
    converged, nf, x, fx, gx = out[:5]
    assert bool(converged.numpy())
    np.testing.assert_allclose(x.numpy(), _QUAD_MIN, atol=1e-4)
    assert float(jnp.max(jnp.abs(gx._value))) < 1e-3
    assert int(nf.numpy()) > 0


@pytest.mark.parametrize("minimize", [minimize_bfgs, minimize_lbfgs])
def test_rosenbrock_converges(minimize):
    # NOTE: the framework 64-bit policy keeps jax_enable_x64 off, so
    # this executes in f32 — tolerances are f32-appropriate
    out = minimize(_rosenbrock, np.asarray([-1.2, 1.0], "f4"),
                   max_iters=200)
    converged, nf, x, fx, gx = out[:5]
    np.testing.assert_allclose(x.numpy(), [1.0, 1.0], atol=5e-3)
    assert float(fx.numpy()) < 1e-5


def test_lbfgs_small_history_ring_buffer():
    out = minimize_lbfgs(_rosenbrock, np.asarray([-1.2, 1.0], "f4"),
                         history_size=3, max_iters=300)
    _, _, x, fx, _ = out[:5]
    np.testing.assert_allclose(x.numpy(), [1.0, 1.0], atol=5e-3)


def test_lbfgs_initial_inverse_hessian_seed_used():
    """The provided H0 seed must change the iterates (it preconditions
    the two-loop recursion)."""
    H0 = np.diag([1.0, 0.01]).astype("f4")
    out_a = minimize_lbfgs(_quad, np.asarray([0.0, 0.0], "f4"),
                           max_iters=1)
    out_b = minimize_lbfgs(_quad, np.asarray([0.0, 0.0], "f4"),
                           max_iters=1,
                           initial_inverse_hessian_estimate=H0)
    assert not np.allclose(out_a[2].numpy(), out_b[2].numpy())


def test_bfgs_tensor_objective_and_initial_position():
    # objective written against the paddle Tensor API, Tensor x0
    def f(x):
        return ((x - paddle.to_tensor(np.asarray([2.0, -1.0], "f4"))) ** 2
                ).sum()
    out = minimize_bfgs(f, paddle.to_tensor(np.zeros(2, "f4")))
    _, _, x, fx, _ = out[:5]
    np.testing.assert_allclose(x.numpy(), [2.0, -1.0], atol=1e-4)
