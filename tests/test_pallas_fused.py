"""Fused norm / AdamW kernels: CPU-fallback numerics vs plain
implementations, grads via custom VJP (reference pattern: OpTest numeric
checks for fused kernels, test/legacy_test/test_fused_*).  The Pallas TPU
path shares this code; tests exercise the fallback numerics + vjp."""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.fused_norm import (
    fused_layer_norm, fused_rms_norm)
from paddle_tpu.ops.pallas.fused_adamw import fused_adamw


class TestFusedNorm:
    def test_layer_norm_matches_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 6, 32).astype("f4")
        g = rng.randn(32).astype("f4")
        b = rng.randn(32).astype("f4")
        y = fused_layer_norm(jnp.asarray(x), jnp.asarray(g),
                             jnp.asarray(b))
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(var + 1e-5) * g + b
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_layer_norm_grads(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(3, 16).astype("f4"))
        g = jnp.asarray(rng.randn(16).astype("f4"))
        b = jnp.asarray(rng.randn(16).astype("f4"))

        def f(x, g, b):
            return jnp.sum(fused_layer_norm(x, g, b) ** 2)

        def ref(x, g, b):
            mu = jnp.mean(x, -1, keepdims=True)
            var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
            return jnp.sum(((x - mu) / jnp.sqrt(var + 1e-5) * g + b) ** 2)

        got = jax.grad(f, argnums=(0, 1, 2))(x, g, b)
        want = jax.grad(ref, argnums=(0, 1, 2))(x, g, b)
        for a, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-3, atol=1e-4)

    def test_rms_norm_matches_numpy_and_grads(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(5, 24).astype("f4"))
        g = jnp.asarray(rng.randn(24).astype("f4"))
        y = fused_rms_norm(x, g)
        xf = np.asarray(x)
        ref = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-6) \
            * np.asarray(g)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4,
                                   atol=1e-5)

        def f(x, g):
            return jnp.sum(fused_rms_norm(x, g) ** 2)

        def fr(x, g):
            ms = jnp.mean(x * x, -1, keepdims=True)
            return jnp.sum((x * jax.lax.rsqrt(ms + 1e-6) * g) ** 2)

        got = jax.grad(f, argnums=(0, 1))(x, g)
        want = jax.grad(fr, argnums=(0, 1))(x, g)
        for a, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-3, atol=1e-4)

    def test_bf16_input_fp32_stats(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(4, 32).astype("f4") * 100,
                        jnp.bfloat16)
        g = jnp.ones(32, jnp.bfloat16)
        b = jnp.zeros(32, jnp.bfloat16)
        y = fused_layer_norm(x, g, b)
        assert y.dtype == jnp.bfloat16
        yf = np.asarray(y, np.float32)
        assert np.abs(yf.mean(-1)).max() < 0.05   # normalized in fp32


class TestFusedAdamW:
    def test_matches_reference_update(self):
        rng = np.random.RandomState(0)
        shapes = [(8, 16), (16,), (3, 5, 7)]
        ps = [jnp.asarray(rng.randn(*s).astype("f4")) for s in shapes]
        gs = [jnp.asarray(rng.randn(*s).astype("f4")) for s in shapes]
        ms = [jnp.zeros(s, jnp.float32) for s in shapes]
        vs = [jnp.zeros(s, jnp.float32) for s in shapes]
        lr, b1, b2, eps, wd = 1e-2, 0.9, 0.95, 1e-8, 0.1
        mask = [1.0, 0.0, 1.0]   # no decay on the bias-shaped param

        np_, nm, nv = fused_adamw(ps, gs, ms, vs, lr, b1, b2, eps, wd,
                                  step=1, decay_mask=mask)
        for p, g, m, v, dm, pn, mn, vn in zip(ps, gs, ms, vs, mask,
                                              np_, nm, nv):
            em = (1 - b1) * np.asarray(g)
            ev = (1 - b2) * np.asarray(g) ** 2
            mhat = em / (1 - b1)
            vhat = ev / (1 - b2)
            upd = mhat / (np.sqrt(vhat) + eps) + wd * dm * np.asarray(p)
            np.testing.assert_allclose(np.asarray(pn),
                                       np.asarray(p) - lr * upd,
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(mn), em, rtol=1e-6)
            np.testing.assert_allclose(np.asarray(vn), ev, rtol=1e-6)

    def test_multi_step_bias_correction(self):
        rng = np.random.RandomState(1)
        p = [jnp.asarray(rng.randn(32).astype("f4"))]
        g = [jnp.asarray(rng.randn(32).astype("f4"))]
        m = [jnp.zeros(32, jnp.float32)]
        v = [jnp.zeros(32, jnp.float32)]
        # two fused steps == two hand-rolled steps
        ref_p, ref_m, ref_v = np.asarray(p[0]), np.zeros(32), np.zeros(32)
        for t in (1, 2):
            p, m, v = fused_adamw(p, g, m, v, 1e-3, 0.9, 0.999, 1e-8,
                                  0.01, step=t)
            ref_m = 0.9 * ref_m + 0.1 * np.asarray(g[0])
            ref_v = 0.999 * ref_v + 0.001 * np.asarray(g[0]) ** 2
            mh = ref_m / (1 - 0.9 ** t)
            vh = ref_v / (1 - 0.999 ** t)
            ref_p = ref_p - 1e-3 * (mh / (np.sqrt(vh) + 1e-8)
                                    + 0.01 * ref_p)
        np.testing.assert_allclose(np.asarray(p[0]), ref_p, rtol=1e-5,
                                   atol=1e-6)


def test_adamw_use_multi_tensor_parity():
    """AdamW(use_multi_tensor=True) routes through the fused kernel (on
    TPU; jnp fallback elsewhere) and matches the per-tensor path,
    including decoupled-decay exclusion by name (VERDICT r2 #8)."""
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.optimizer.optimizer import AdamW

    rng = np.random.RandomState(0)
    ps = [jnp.asarray(rng.randn(8, 8), jnp.float32),
          jnp.asarray(rng.randn(16,), jnp.float32)]
    gs = [jnp.asarray(rng.randn(8, 8), jnp.float32),
          jnp.asarray(rng.randn(16,), jnp.float32)]
    names = ["fc_weight", "fc_bias"]

    def run(use_mt):
        opt = AdamW(learning_rate=1e-2, weight_decay=0.1,
                    use_multi_tensor=use_mt,
                    apply_decay_param_fun=lambda n: "bias" not in n)
        st = [opt._init_state_for(p) for p in ps]
        out_p, out_s = ps, st
        for _ in range(3):
            out_p, out_s = opt.apply_functional(out_p, gs, out_s, 1e-2,
                                                param_names=names)
        return out_p, out_s

    p_ref, s_ref = run(False)
    p_mt, s_mt = run(True)
    for a, b in zip(p_ref, p_mt):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    for a, b in zip(s_ref, s_mt):
        np.testing.assert_allclose(np.asarray(a["moment1"]),
                                   np.asarray(b["moment1"]), rtol=2e-5,
                                   atol=2e-6)
        np.testing.assert_allclose(float(a["beta1_pow"]),
                                   float(b["beta1_pow"]), rtol=1e-6)


def test_adamw_multi_tensor_per_param_bias_correction():
    """Params at different step counts (freeze/unfreeze) must get their
    OWN bias correction in the fused path (review r3)."""
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.optimizer.optimizer import AdamW

    rng = np.random.RandomState(1)
    ps = [jnp.asarray(rng.randn(8, 8), jnp.float32),
          jnp.asarray(rng.randn(8, 8), jnp.float32)]
    g1 = [None, jnp.asarray(rng.randn(8, 8), jnp.float32)]
    g2 = [jnp.asarray(rng.randn(8, 8), jnp.float32),
          jnp.asarray(rng.randn(8, 8), jnp.float32)]

    def run(use_mt):
        opt = AdamW(learning_rate=1e-2, weight_decay=0.0,
                    use_multi_tensor=use_mt)
        st = [opt._init_state_for(p) for p in ps]
        out_p, out_s = ps, st
        # 5 steps with param 0 frozen, then 3 with both live
        for _ in range(5):
            out_p, out_s = opt.apply_functional(out_p, g1, out_s, 1e-2)
        for _ in range(3):
            out_p, out_s = opt.apply_functional(out_p, g2, out_s, 1e-2)
        return out_p

    p_ref = run(False)
    p_mt = run(True)
    for a, b in zip(p_ref, p_mt):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_conv1x1_bn_act_matches_dense():
    """Fused 1x1-conv+BN+ReLU(+residual) matmul kernel (VERDICT r3 #6)
    vs the unfused reference, interpret mode."""
    from paddle_tpu.ops.pallas.conv1x1 import (conv1x1_bn_act,
                                               conv1x1_bn_act_nhwc)
    rng = np.random.RandomState(0)
    M, K, N = 800, 256, 128          # M % block_m != 0 -> padding path
    x = jnp.asarray(rng.randn(M, K).astype("f4"))
    w = jnp.asarray(rng.randn(K, N).astype("f4") * 0.05)
    sc = jnp.asarray(rng.rand(N).astype("f4") + 0.5)
    sh = jnp.asarray(rng.randn(N).astype("f4"))
    res = jnp.asarray(rng.randn(M, N).astype("f4"))
    ref = np.maximum((np.asarray(x) @ np.asarray(w)) * np.asarray(sc)
                     + np.asarray(sh) + np.asarray(res), 0)
    out = conv1x1_bn_act(x, w, sc, sh, residual=res, relu=True,
                         interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)
    # NHWC wrapper
    xb = jnp.asarray(rng.randn(2, 8, 8, 64).astype("f4"))
    wb = jnp.asarray(rng.randn(64, 128).astype("f4") * 0.05)
    scb = jnp.ones(128, "f4")
    shb = jnp.zeros(128, "f4")
    outb = conv1x1_bn_act_nhwc(xb, wb, scb, shb, relu=False,
                               interpret=True)
    refb = np.asarray(xb).reshape(-1, 64) @ np.asarray(wb)
    np.testing.assert_allclose(np.asarray(outb).reshape(-1, 128), refb,
                               rtol=2e-4, atol=2e-4)
