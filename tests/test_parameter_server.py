"""Parameter-server mode: sharded sparse/dense tables, push/pull,
server-side accessors, geo-async deltas, persistence (reference:
paddle/fluid/distributed/ps/ + the_one_ps.py runtime; tests modeled on
test/legacy_test PS unit patterns — in-process server threads stand in
for brpc services)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (
    PSServer, PSClient, GeoSparseTable)


@pytest.fixture
def cluster():
    servers = [PSServer() for _ in range(2)]
    client = PSClient([f"127.0.0.1:{s.port}" for s in servers])
    yield servers, client
    client.close()
    for s in servers:
        s.stop()


class TestSparseTable:
    def test_pull_initializes_and_is_stable(self, cluster):
        _, client = cluster
        client.create_sparse_table("emb", dim=4, seed=3)
        ids = [0, 1, 5, 9, 1]
        rows = client.pull_sparse("emb", ids)
        assert rows.shape == (5, 4)
        rows2 = client.pull_sparse("emb", ids)
        np.testing.assert_array_equal(rows, rows2)   # rows persist
        np.testing.assert_array_equal(rows[1], rows[4])  # same id

    def test_push_applies_sgd(self, cluster):
        _, client = cluster
        client.create_sparse_table("emb", dim=3, rule="sgd", lr=0.1)
        ids = [2, 7]       # one per shard (2 % 2 = 0, 7 % 2 = 1)
        before = client.pull_sparse("emb", ids)
        g = np.ones((2, 3), np.float32)
        client.push_sparse("emb", ids, g)
        after = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(after, before - 0.1 * g, rtol=1e-6)

    def test_adagrad_accessor(self, cluster):
        _, client = cluster
        client.create_sparse_table("emb", dim=2, rule="adagrad", lr=1.0)
        before = client.pull_sparse("emb", [4])
        g = np.full((1, 2), 2.0, np.float32)
        client.push_sparse("emb", [4], g)
        after = client.pull_sparse("emb", [4])
        # adagrad: row -= lr * g / (sqrt(g^2) + eps) ≈ row - 1.0
        np.testing.assert_allclose(after, before - 1.0, atol=1e-4)

    def test_batched_2d_ids(self, cluster):
        _, client = cluster
        client.create_sparse_table("emb", dim=4)
        ids = np.arange(6).reshape(2, 3)
        rows = client.pull_sparse("emb", ids)
        assert rows.shape == (2, 3, 4)


class TestDenseTable:
    def test_push_pull(self, cluster):
        _, client = cluster
        w0 = np.arange(6, dtype=np.float32).reshape(2, 3)
        client.create_dense_table("w", shape=(2, 3), init=w0.tolist(),
                                  lr=0.5)
        np.testing.assert_array_equal(client.pull_dense("w"), w0)
        g = np.ones((2, 3), np.float32)
        client.push_dense("w", g)
        np.testing.assert_allclose(client.pull_dense("w"), w0 - 0.5 * g)


class TestPersistence:
    def test_save_load_roundtrip(self, cluster, tmp_path):
        servers, client = cluster
        client.create_sparse_table("emb", dim=4)
        rows = client.pull_sparse("emb", list(range(8)))
        client.save_persistables(str(tmp_path / "ps"))

        # new cluster loads the snapshot and serves identical rows
        servers2 = [PSServer() for _ in range(2)]
        client2 = PSClient([f"127.0.0.1:{s.port}" for s in servers2])
        try:
            client2.create_sparse_table("emb", dim=4, seed=999)
            client2.load_persistables(str(tmp_path / "ps"))
            rows2 = client2.pull_sparse("emb", list(range(8)))
            np.testing.assert_array_equal(rows, rows2)
        finally:
            client2.close()
            for s in servers2:
                s.stop()


class TestGeoAsync:
    def test_deltas_merge_from_two_workers(self, cluster):
        _, client = cluster
        client.create_sparse_table("emb", dim=2, rule="sum")
        base = client.pull_sparse("emb", [3])[0]
        w1 = GeoSparseTable(client, "emb", lr=0.5, geo_step=100)
        w2 = GeoSparseTable(client, "emb", lr=0.5, geo_step=100)
        g1 = np.array([[1.0, 0.0]], np.float32)
        g2 = np.array([[0.0, 2.0]], np.float32)
        w1.pull([3]); w1.push([3], g1)
        w2.pull([3]); w2.push([3], g2)
        w1.flush(); w2.flush()
        merged = client.pull_sparse("emb", [3])[0]
        np.testing.assert_allclose(
            merged, base - 0.5 * (g1[0] + g2[0]), rtol=1e-6)
        # after flush both workers' caches converge to the merged row
        np.testing.assert_allclose(w2.cache[3], merged, rtol=1e-6)

    def test_auto_flush_every_geo_step(self, cluster):
        _, client = cluster
        client.create_sparse_table("emb", dim=2, rule="sum")
        w = GeoSparseTable(client, "emb", lr=1.0, geo_step=2)
        base = client.pull_sparse("emb", [11])[0]
        g = np.array([[1.0, 1.0]], np.float32)
        w.pull([11]); w.push([11], g)
        np.testing.assert_array_equal(
            client.pull_sparse("emb", [11])[0], base)  # not yet flushed
        w.push([11], g)                                # geo_step reached
        np.testing.assert_allclose(
            client.pull_sparse("emb", [11])[0], base - 2.0 * g[0])


class TestEndToEndTraining:
    def test_sparse_embedding_model_learns(self, cluster):
        """Tiny recsys: loss falls when embeddings train via push/pull
        around the normal autograd tape (the worker-side integration)."""
        _, client = cluster
        dim = 8
        client.create_sparse_table("emb", dim=dim, rule="sgd", lr=0.3,
                                   seed=0)
        rng = np.random.RandomState(0)
        w = paddle.to_tensor(rng.randn(dim, 1).astype("f4") * 0.3)
        w.stop_gradient = False
        ids = np.array([1, 2, 3, 4], np.int64)
        target = paddle.to_tensor(
            rng.rand(len(ids), 1).astype("f4"))

        losses = []
        for _ in range(30):
            rows = client.pull_sparse("emb", ids)
            emb = paddle.to_tensor(rows)
            emb.stop_gradient = False
            pred = paddle.matmul(emb, w)
            loss = ((pred - target) ** 2).mean()
            loss.backward()
            client.push_sparse("emb", ids, emb.grad.numpy())
            w_new = w - 0.3 * paddle.to_tensor(w.grad.numpy())
            w = paddle.to_tensor(w_new.numpy())
            w.stop_gradient = False
            losses.append(float(loss))
        assert losses[-1] < 0.25 * losses[0], \
            f"PS training failed to learn: {losses[0]} -> {losses[-1]}"


class TestWireHardening:
    """VERDICT r2 #7: the PS wire must reject frames whose pickle
    references non-numpy globals (no arbitrary-code execution)."""

    def test_malicious_frame_rejected(self, cluster):
        import pickle
        import socket
        import struct
        servers, _ = cluster

        class Evil:
            def __reduce__(self):
                import os
                return (os.system, ("echo pwned > /tmp/ps_pwned",))

        payload = pickle.dumps({"op": Evil()})
        with socket.create_connection(("127.0.0.1", servers[0].port),
                                      timeout=10) as s:
            s.sendall(struct.pack("!I", len(payload)) + payload)
            hdr = s.recv(4)
            (n,) = struct.unpack("!I", hdr)
            buf = b""
            while len(buf) < n:
                buf += s.recv(n - len(buf))
        resp = pickle.loads(buf)
        assert resp["ok"] is False
        assert "refusing to unpickle" in resp["error"]
        import os
        assert not os.path.exists("/tmp/ps_pwned"), \
            "malicious payload EXECUTED"

    def test_legit_frames_still_work_after_rejection(self, cluster):
        servers, client = cluster
        client.create_sparse_table("t", dim=4)
        rows = client.pull_sparse("t", [1, 2, 3])
        assert rows.shape == (3, 4)

    def test_restricted_loads_roundtrips_numpy(self):
        import pickle
        from paddle_tpu.distributed.ps import _safe_loads
        obj = {"op": "push_sparse", "ids": [1, 2],
               "grads": np.random.randn(2, 4).astype(np.float32),
               "scalar": np.float32(1.5), "nested": {"a": (1, 2.0, None)}}
        out = _safe_loads(pickle.dumps(obj, pickle.HIGHEST_PROTOCOL))
        np.testing.assert_array_equal(out["grads"], obj["grads"])
        assert out["nested"]["a"] == (1, 2.0, None)
