"""SPMD pipeline parity: pipelined stacked blocks == sequential run
(reference pattern: hybrid_parallel_pp_alexnet.py — PP run equals single
 -process golden)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.pipeline import (
    spmd_pipeline, stack_block_params, PipelineStagedModule)


def _mesh_pipe(S=4):
    devs = np.asarray(jax.devices()[:S])
    return Mesh(devs, ("pipe",))


def test_spmd_pipeline_matches_sequential():
    rng = np.random.RandomState(0)
    L, M, mb, H = 8, 4, 2, 16   # 8 blocks, 4 stages, 4 microbatches
    Ws = [rng.randn(H, H).astype("f4") * 0.3 for _ in range(L)]
    bs = [rng.randn(H).astype("f4") * 0.1 for _ in range(L)]
    x = rng.randn(M, mb, H).astype("f4")

    def block_apply(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    stacked = stack_block_params([[W, b] for W, b in zip(Ws, bs)])
    mesh = _mesh_pipe(4)
    out = spmd_pipeline(block_apply, stacked, jnp.asarray(x), mesh)

    ref = x.copy()
    for W, b in zip(Ws, bs):
        ref = np.tanh(ref @ W + b)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_spmd_pipeline_grad_flows():
    rng = np.random.RandomState(1)
    L, M, mb, H = 4, 2, 2, 8
    Ws = [rng.randn(H, H).astype("f4") * 0.3 for _ in range(L)]
    x = jnp.asarray(rng.randn(M, mb, H).astype("f4"))

    def block_apply(params, h):
        (W,) = params
        return jnp.tanh(h @ W)

    stacked = stack_block_params([[W] for W in Ws])
    mesh = _mesh_pipe(2)

    def loss_fn(stacked_, x_):
        out = spmd_pipeline(block_apply, stacked_, x_, mesh)
        return jnp.sum(out ** 2)

    g = jax.grad(loss_fn)(stacked, x)
    # reference grad via plain sequential computation
    def ref_loss(stacked_, x_):
        h = x_
        for i in range(L):
            h = jnp.tanh(h @ stacked_[0][i])
        return jnp.sum(h ** 2)
    g_ref = jax.grad(ref_loss)(stacked, x)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]),
                               rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("S,V,M", [(2, 2, 2), (2, 2, 4), (4, 2, 8),
                                   (2, 4, 3)])
def test_spmd_pipeline_interleaved_matches_sequential(S, V, M):
    # interleaved virtual stages: logical stage l=v*S+s on physical s,
    # activations make V ppermute round trips — must equal sequential
    rng = np.random.RandomState(2)
    L, mb, H = S * V * 2, 2, 8   # per-chunk = 2 layers
    Ws = [rng.randn(H, H).astype("f4") * 0.3 for _ in range(L)]
    x = rng.randn(M, mb, H).astype("f4")

    def block_apply(params, h):
        (W,) = params
        return jnp.tanh(h @ W)

    stacked = stack_block_params([[W] for W in Ws])
    mesh = _mesh_pipe(S)
    out = spmd_pipeline(block_apply, stacked, jnp.asarray(x), mesh,
                        n_virtual=V)
    ref = x.copy()
    for W in Ws:
        ref = np.tanh(ref @ W)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_spmd_pipeline_interleaved_grad_flows():
    rng = np.random.RandomState(3)
    S, V, M, mb, H = 2, 2, 4, 2, 8
    L = S * V
    Ws = [rng.randn(H, H).astype("f4") * 0.3 for _ in range(L)]
    x = jnp.asarray(rng.randn(M, mb, H).astype("f4"))

    def block_apply(params, h):
        (W,) = params
        return jnp.tanh(h @ W)

    stacked = stack_block_params([[W] for W in Ws])
    mesh = _mesh_pipe(S)

    def loss_fn(stacked_):
        out = spmd_pipeline(block_apply, stacked_, x, mesh, n_virtual=V)
        return jnp.sum(out ** 2)

    def ref_loss(stacked_):
        h = x
        for i in range(L):
            h = jnp.tanh(h @ stacked_[0][i])
        return jnp.sum(h ** 2)

    g = jax.grad(loss_fn)(stacked)
    g_ref = jax.grad(ref_loss)(stacked)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]),
                               rtol=1e-3, atol=1e-4)


def test_staged_module_gpt_blocks():
    from paddle_tpu.models.gpt import gpt3_tiny, GPTDecoderLayer
    paddle.seed(0)
    cfg = gpt3_tiny()
    blocks = [GPTDecoderLayer(cfg) for _ in range(4)]
    for b in blocks:
        b.eval()
    mesh = _mesh_pipe(2)
    staged = PipelineStagedModule(blocks, mesh, remat=False)
    rng = np.random.RandomState(0)
    x = rng.randn(2, 1, 8, cfg.hidden_size).astype("f4")  # (M, mb, S, H)
    out = staged.apply(staged.stacked, jnp.asarray(x))

    ref = paddle.to_tensor(x.reshape(2, 8, cfg.hidden_size))
    with paddle.no_grad():
        for b in blocks:
            ref = b(ref)
    np.testing.assert_allclose(
        np.asarray(out).reshape(2, 8, cfg.hidden_size), ref.numpy(),
        rtol=1e-4, atol=1e-4)
