"""PipelineLayer/LayerDesc API + PipelineParallel train_batch
(reference: test/collective/fleet/hybrid_parallel_pp_*.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, SharedLayerDesc, PipelineLayer)


class Block(nn.Layer):
    def __init__(self, h=16):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        return F.relu(self.fc(x))


def test_layer_desc_build_and_forward():
    pl = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 16)] +
               [LayerDesc(Block, 16) for _ in range(4)] +
               [LayerDesc(nn.Linear, 16, 4)],
        num_stages=2)
    x = paddle.randn([2, 8])
    out = pl(x)
    assert out.shape == [2, 4]
    cuts = pl.segment()
    assert cuts[0] == 0 and cuts[-1] == 6 and len(cuts) == 3


def test_homogeneous_run_detection():
    pl = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 16)] +
               [LayerDesc(Block, 16) for _ in range(4)] +
               [LayerDesc(nn.Linear, 16, 4)],
        num_stages=2)
    head, mid, tail = pl.homogeneous_run()
    assert len(mid) == 4
    assert len(head) == 1 and len(tail) == 1


def test_shared_layer_desc_ties_weights():
    pl = PipelineLayer(layers=[
        SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
        LayerDesc(Block, 8),
        SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
    ], num_stages=1)
    layers = [l for l, _ in pl.run_function]
    assert layers[0] is layers[2], "shared descs must reuse the layer"


def test_pipeline_parallel_train_batch():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 4}
    fleet.init(is_collective=True, strategy=strategy)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = fleet.distributed_model(net)
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
        import PipelineParallel
    assert isinstance(model, PipelineParallel)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    x = np.random.RandomState(0).rand(8, 8).astype("f4")
    y = np.random.RandomState(1).rand(8, 4).astype("f4")
    loss_fn = nn.MSELoss()
    w_before = net[0].weight.numpy().copy()
    loss = model.train_batch([x, y], opt, loss_fn=loss_fn)
    assert np.isfinite(float(loss))
    assert not np.allclose(net[0].weight.numpy(), w_before), \
        "optimizer must have stepped"
