"""Profiler coverage (ISSUE 5 satellite): scheduler state machine,
RecordEvent fallback collection, export -> load_profiler_result
round-trip, step_info, summary(views=)."""
import json
import os

import pytest

import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (Profiler, ProfilerResult, ProfilerState,
                                 RecordEvent, SummaryView, make_scheduler,
                                 load_profiler_result)


@pytest.fixture
def py_tracer(monkeypatch):
    """Force the pure-Python span collection (the native C++ tracer,
    when built, would otherwise swallow spans) and arm it."""
    monkeypatch.setattr(profiler, "_native_tracer", lambda: None)
    profiler._HOST_EVENTS.clear()
    profiler._COLLECTING[0] = True
    yield
    profiler._COLLECTING[0] = False
    profiler._HOST_EVENTS.clear()


class TestMakeScheduler:
    def test_closed_ready_record_sequence(self):
        sched = make_scheduler(closed=2, ready=1, record=2)
        states = [sched(i) for i in range(5)]
        assert states == [ProfilerState.CLOSED, ProfilerState.CLOSED,
                          ProfilerState.READY, ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN]

    def test_cycle_repeats_without_repeat_limit(self):
        sched = make_scheduler(closed=1, ready=1, record=1)
        assert [sched(i) for i in range(6)] == [
            ProfilerState.CLOSED, ProfilerState.READY,
            ProfilerState.RECORD_AND_RETURN] * 2

    def test_skip_first_then_cycle(self):
        sched = make_scheduler(closed=1, ready=0, record=1, skip_first=2)
        # steps 0,1 skipped; then closed, then record-and-return
        assert [sched(i) for i in range(4)] == [
            ProfilerState.CLOSED, ProfilerState.CLOSED,
            ProfilerState.CLOSED, ProfilerState.RECORD_AND_RETURN]

    def test_repeat_limit_closes_for_good(self):
        sched = make_scheduler(closed=0, ready=0, record=1, repeat=2)
        assert sched(0) == ProfilerState.RECORD_AND_RETURN
        assert sched(1) == ProfilerState.RECORD_AND_RETURN
        assert sched(2) == ProfilerState.CLOSED
        assert sched(99) == ProfilerState.CLOSED


class TestRecordEventFallback:
    def test_span_collected_with_name_and_type(self, py_tracer):
        with RecordEvent("my_span"):
            pass
        with RecordEvent("op_span", event_type="Operator"):
            pass
        events = profiler._collect_events()
        names = {e.name for e in events}
        assert {"my_span", "op_span"} <= names
        (udf,) = [e for e in events if e.name == "my_span"]
        assert udf.event_type == "UserDefined"
        assert udf.end >= udf.start

    def test_not_collected_when_disarmed(self, py_tracer):
        profiler._COLLECTING[0] = False
        with RecordEvent("ghost"):
            pass
        assert all(e.name != "ghost" for e in profiler._collect_events())

    def test_begin_end_explicit_api(self, py_tracer):
        ev = RecordEvent("explicit")
        ev.begin()
        ev.end()
        assert any(e.name == "explicit"
                   for e in profiler._collect_events())


class TestExportLoadRoundTrip:
    def test_round_trip_names_types_durations(self, py_tracer, tmp_path):
        with RecordEvent("alpha"):
            with RecordEvent("beta", event_type="Operator"):
                pass
        path = os.path.join(str(tmp_path), "trace.json")
        prof = Profiler(timer_only=True)
        out = prof.export(path)
        assert out == path
        res = load_profiler_result(path)
        assert isinstance(res, ProfilerResult) and len(res) == 2
        exported = {e.name: e for e in profiler._collect_events()}
        for e in res:
            src = exported[e.name]
            assert str(e.event_type) == str(src.event_type)
            # µs-precision round-trip on the same clock base
            assert abs(e.start - src.start) < 1000
            assert abs((e.end - e.start) - (src.end - src.start)) < 1000

    def test_query_by_name_and_view(self, py_tracer, tmp_path):
        with RecordEvent("udf"):
            pass
        with RecordEvent("fw", event_type="Framework"):
            pass
        path = Profiler(timer_only=True).export(
            os.path.join(str(tmp_path), "t.json"))
        res = load_profiler_result(path)
        assert [e.name for e in res.query(name="udf")] == ["udf"]
        assert {e.name for e in res.query(view=SummaryView.UDFView)} \
            == {"udf"}
        assert {e.name for e in res.query(view=SummaryView.OperatorView)} \
            == {"fw"}

    def test_missing_file_returns_none(self, tmp_path):
        assert load_profiler_result(
            os.path.join(str(tmp_path), "nope.json")) is None

    def test_non_trace_json_raises(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"hello": 1}))
        with pytest.raises(ValueError, match="traceEvents"):
            load_profiler_result(str(p))


class TestSummaryViews:
    def test_views_filters_udf_vs_operator(self, py_tracer):
        with RecordEvent("user_thing"):
            pass
        with RecordEvent("op_thing", event_type="Operator"):
            pass
        prof = Profiler(timer_only=True)
        udf = prof.summary(views=SummaryView.UDFView)
        assert "user_thing" in udf and "op_thing" not in udf
        ops = prof.summary(views=[SummaryView.OperatorView])
        assert "op_thing" in ops and "user_thing" not in ops
        both = prof.summary()
        assert "user_thing" in both and "op_thing" in both

    def test_device_only_views_render_header_only(self, py_tracer):
        with RecordEvent("host_span"):
            pass
        out = Profiler(timer_only=True).summary(
            views=[SummaryView.KernelView])
        assert "Summary" in out and "host_span" not in out


class TestStepInfo:
    def test_empty_then_populated(self):
        prof = Profiler(timer_only=True)
        assert prof.step_info() == ""
        prof.start()
        prof.step()
        prof.step()
        prof.stop()
        info = prof.step_info()
        assert "avg_step_time" in info and "ms" in info
        assert prof._step == 2
