"""Int8 matmul Pallas kernel (reference pattern: cutlass int8 GEMM
epilogue tests).  Runs in pallas interpret mode off-TPU."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.quant_matmul import int8_matmul


def _golden(x, w_int, w_scale, a_s, bnd=127.0):
    xq = np.clip(np.round(x.astype("f8") / a_s * bnd), -bnd - 1, bnd)
    acc = xq.astype("i8").astype("i4") @ w_int.astype("i4")
    return acc.astype("f8") * (a_s / bnd) * (w_scale.astype("f8") / bnd)


def _mk(M, K, N, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(M, K).astype("f4")
    w_int = rng.randint(-127, 128, (K, N)).astype(np.int8)
    w_scale = (0.5 + rng.rand(N)).astype("f4")
    a_s = float(np.abs(x).max())
    return x, w_int, w_scale, a_s


def test_int8_matmul_matches_golden():
    x, w_int, w_scale, a_s = _mk(32, 64, 16)
    out = int8_matmul(jnp.asarray(x), jnp.asarray(w_int),
                      jnp.asarray(w_scale), a_s, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _golden(x, w_int, w_scale, a_s),
                               rtol=1e-4, atol=1e-4)


def test_int8_matmul_padded_blocks():
    # M/K/N not multiples of the block sizes: exercises the pad path and
    # the k-loop accumulation across two K blocks
    x, w_int, w_scale, a_s = _mk(300, 600, 130, seed=1)
    out = int8_matmul(jnp.asarray(x), jnp.asarray(w_int),
                      jnp.asarray(w_scale), a_s, interpret=True)
    assert out.shape == (300, 130)
    np.testing.assert_allclose(np.asarray(out),
                               _golden(x, w_int, w_scale, a_s),
                               rtol=1e-4, atol=1e-4)


def test_int8_matmul_leading_dims():
    x, w_int, w_scale, a_s = _mk(24, 32, 16, seed=2)
    x3 = x.reshape(2, 12, 32)
    out = int8_matmul(jnp.asarray(x3), jnp.asarray(w_int),
                      jnp.asarray(w_scale), a_s, interpret=True)
    assert out.shape == (2, 12, 16)
    np.testing.assert_allclose(np.asarray(out).reshape(24, 16),
                               _golden(x, w_int, w_scale, a_s),
                               rtol=1e-4, atol=1e-4)


def test_quantized_linear_uses_same_math():
    """QuantizedLinear's CPU fallback == the kernel numerics."""
    from paddle_tpu.quantization import ConvertedQuantedLinear
    import paddle_tpu as paddle
    x, w_int, w_scale, a_s = _mk(8, 16, 4, seed=3)
    lin = ConvertedQuantedLinear(w_int, w_scale * 127.0, None, act_scale=a_s)
    ref = lin(paddle.to_tensor(x))
    out = int8_matmul(jnp.asarray(x), jnp.asarray(w_int),
                      jnp.asarray(w_scale * 127.0), a_s, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref._value),
                               rtol=1e-4, atol=1e-4)


def test_fp8_matmul_close_to_fp32():
    """fp8 e4m3 quantized matmul stays within fp8 tolerance of the fp32
    product (SURVEY fp8 epilogue row) in all three act_scale modes:
    None = weight-only (default, activations stay bf16), "dynamic" =
    per-call amax activation quantization, float = static act scale."""
    import numpy as np
    import jax.numpy as jnp
    import pytest
    from paddle_tpu.ops.pallas.quant_matmul import (
        fp8_matmul, fp8_quantize_weight)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 64).astype("f4")
    w = rng.randn(64, 48).astype("f4")
    w8, ws = fp8_quantize_weight(w)
    assert str(w8.dtype) == "float8_e4m3fn"
    ref = x @ w
    # weight-only default — only the weight carries quant error
    out = fp8_matmul(x, w8, ws)
    rel = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
    assert rel < 0.08, rel
    # static act_scale path (weight + act quantized)
    out2 = fp8_matmul(x, w8, ws, act_scale=float(np.abs(x).max() / 448.0))
    rel2 = np.abs(np.asarray(out2) - ref).max() / np.abs(ref).max()
    assert rel2 < 0.08, rel2
    # dynamic act quantization must match the equivalent static scale
    out3 = fp8_matmul(x, w8, ws, act_scale="dynamic")
    np.testing.assert_allclose(np.asarray(out3), np.asarray(out2),
                               rtol=1e-5, atol=1e-5)
    # act-quantized paths carry MORE error than weight-only
    rel3 = np.abs(np.asarray(out3) - ref).max() / np.abs(ref).max()
    assert rel3 >= rel or rel < 0.01
    with pytest.raises(ValueError, match="act_scale"):
        fp8_matmul(x, w8, ws, act_scale="Dynamic")
