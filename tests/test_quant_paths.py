"""Quantized hot paths (ISSUE 19): int8/fp8 quant_matmul behind the
kernel registry, the serving engine's ``quant_mode`` weight pass, and
the hapi fp8 train pilot.

Tolerance contracts (docs/kernels.md "Quantized matmul",
docs/serving.md "Quantized decode"):

- int8 weight round-trip error <= scale/254 per element (half an int8
  step of the channel absmax); full-matmul relative error <= 2% (int8
  with dynamic activation quant) / <= 4% (fp8 e4m3) on unit-scale
  Gaussian data.
- pallas-interpret vs the XLA dot_general reference: identical math,
  tight parity (the interpret-mode CI contract).
- serving greedy decode: quant_mode=None stays BITWISE vs generate();
  quantized engines must agree with the bf16 engine on >= 99% of
  tokens (the int8-KV documented-bound pattern) on every KV mode.
- fp8 train pilot: loss parity within a 5% relative envelope vs the
  unquantized run on the tiny regression model (measured ~2%); amax
  state survives train_state_dict round-trips; a guardian
  ``guardian.poison_batch`` chaos trip skips cleanly with finite amax.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework import failpoints, guardian
from paddle_tpu.hapi import callbacks as cbks_mod
from paddle_tpu.ops import quant_dispatch as qd
from paddle_tpu.ops import registry as kreg
from paddle_tpu.static import InputSpec


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ("PADDLE_TPU_KERNEL_INTERPRET",
                "PADDLE_TPU_KERNEL_QUANT_MATMUL"):
        monkeypatch.delenv(var, raising=False)
    kreg._reset_for_tests()
    failpoints.clear()
    guardian.clear_events()
    guardian.uninstall_sentinel()
    yield
    kreg._reset_for_tests()
    failpoints.clear()
    guardian.clear_events()
    guardian.uninstall_sentinel()


def _wx(M=8, K=64, N=48, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(M, K).astype("f4"), rng.randn(K, N).astype("f4"))


# ---------------------------------------------------------------------------
# quantize_weight: per-channel scale round-trip bounds
# ---------------------------------------------------------------------------

class TestQuantizeWeight:
    def test_int8_roundtrip_bound(self):
        _, w = _wx()
        qw = qd.quantize_weight(jnp.asarray(w), "int8")
        assert qw.mode == "int8" and str(qw.q.dtype) == "int8"
        assert qw.scale.shape == (w.shape[1],)
        np.testing.assert_allclose(np.asarray(qw.scale),
                                   np.abs(w).max(axis=0), rtol=1e-6)
        deq = np.asarray(qw.q, "f4") * np.asarray(qw.scale)[None, :] / 127.0
        # half an int8 step of the channel absmax, per element
        bound = np.asarray(qw.scale)[None, :] / 254.0
        assert (np.abs(deq - w) <= bound + 1e-7).all()

    def test_fp8_roundtrip_bound(self):
        if qd._FP8_DTYPE is None:
            pytest.skip("jax build has no float8_e4m3fn")
        _, w = _wx(seed=1)
        qw = qd.quantize_weight(jnp.asarray(w), "fp8")
        assert qw.mode == "fp8" and str(qw.q.dtype) == "float8_e4m3fn"
        deq = np.asarray(qw.q, "f4") * np.asarray(qw.scale)[None, :]
        # e4m3 keeps ~3 mantissa bits; worst case near the channel max
        # is bounded by one e4m3 step of the absmax
        bound = np.abs(w).max(axis=0)[None, :] / 8.0
        assert (np.abs(deq - w) <= bound + 1e-7).all()

    def test_fp8_degrades_to_int8_when_unavailable(self, monkeypatch):
        monkeypatch.setattr(qd, "_FP8_DTYPE", None)
        reg = paddle.observability.get_registry()
        m0 = reg.get("pt_kernel_fallbacks_total")
        base = (m0.value(kernel="quant_matmul", reason="fp8-unavailable")
                if m0 else 0)
        _, w = _wx()
        qw = qd.quantize_weight(jnp.asarray(w), "fp8")
        assert qw.mode == "int8" and str(qw.q.dtype) == "int8"
        m = reg.get("pt_kernel_fallbacks_total")
        assert m.value(kernel="quant_matmul",
                       reason="fp8-unavailable") == base + 1

    def test_bad_mode_raises(self):
        with pytest.raises(ValueError, match="mode"):
            qd.quantize_weight(jnp.ones((4, 4)), "int4")

    def test_pytree_roundtrip_and_bytes_saved(self):
        _, w = _wx()
        qw = qd.quantize_weight(jnp.asarray(w), "int8")
        leaves, treedef = jax.tree_util.tree_flatten(qw)
        assert len(leaves) == 2
        back = jax.tree_util.tree_unflatten(treedef, leaves)
        assert isinstance(back, qd.QuantizedWeight)
        assert back.mode == "int8" and back.orig_dtype == "float32"
        np.testing.assert_array_equal(np.asarray(back.q),
                                      np.asarray(qw.q))
        k, n = w.shape
        assert qw.bytes_saved() == k * n * 4 - (k * n + n * 4)


# ---------------------------------------------------------------------------
# quant_matmul dispatch: XLA reference vs pallas-interpret parity
# ---------------------------------------------------------------------------

class TestQuantMatmulDispatch:
    def test_cpu_selects_xla(self):
        assert kreg.choose("quant_matmul").impl == "xla"

    def test_int8_close_to_fp32(self):
        x, w = _wx()
        qw = qd.quantize_weight(jnp.asarray(w), "int8")
        out = qd.quant_matmul(jnp.asarray(x), qw)
        ref = x @ w
        rel = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
        assert rel < 0.02, rel

    def test_fp8_close_to_fp32(self):
        if qd._FP8_DTYPE is None:
            pytest.skip("jax build has no float8_e4m3fn")
        x, w = _wx(seed=2)
        qw = qd.quantize_weight(jnp.asarray(w), "fp8")
        out = qd.quant_matmul(jnp.asarray(x), qw)
        ref = x @ w
        rel = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
        assert rel < 0.04, rel

    def test_interpret_mode_matches_xla(self, monkeypatch):
        x, w = _wx(M=24, K=96, N=64, seed=3)
        qw = qd.quantize_weight(jnp.asarray(w), "int8")
        ref = qd.quant_matmul(jnp.asarray(x), qw)     # cpu -> xla
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        kreg._reset_for_tests()
        sel = kreg.choose("quant_matmul")
        assert sel.impl == "pallas" and sel.interpret
        out = qd.quant_matmul(jnp.asarray(x), qw)     # interpret pallas
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_leading_dims_and_out_dtype(self):
        x, w = _wx(M=24, seed=4)
        qw = qd.quantize_weight(jnp.asarray(w), "int8")
        out = qd.quant_matmul(jnp.asarray(x).reshape(2, 12, -1), qw,
                              out_dtype="bfloat16")
        assert out.shape == (2, 12, w.shape[1])
        assert str(out.dtype) == "bfloat16"

    def test_fp8_on_pallas_books_weight_only_fallback(self, monkeypatch):
        if qd._FP8_DTYPE is None:
            pytest.skip("jax build has no float8_e4m3fn")
        x, w = _wx()
        qw = qd.quantize_weight(jnp.asarray(w), "fp8")
        monkeypatch.setenv("PADDLE_TPU_KERNEL_INTERPRET", "1")
        kreg._reset_for_tests()
        reg = paddle.observability.get_registry()
        m0 = reg.get("pt_kernel_fallbacks_total")
        base = (m0.value(kernel="quant_matmul", reason="fp8-weight-only")
                if m0 else 0)
        qd.quant_matmul(jnp.asarray(x), qw)
        m = reg.get("pt_kernel_fallbacks_total")
        assert m.value(kernel="quant_matmul",
                       reason="fp8-weight-only") == base + 1

    def test_eager_dispatch_registers_surface(self):
        from paddle_tpu.observability import compilestats
        x, w = _wx()
        qw = qd.quantize_weight(jnp.asarray(w), "int8")
        qd.quant_matmul(jnp.asarray(x), qw)
        assert kreg.QUANT_MATMUL_SURFACE in compilestats.surfaces()
        st = compilestats.snapshot()[kreg.QUANT_MATMUL_SURFACE]
        assert st["compiles"] >= 1

    def test_traced_dispatch_inlines_into_caller(self):
        from paddle_tpu.observability import compilestats
        x, w = _wx()
        qw = qd.quantize_weight(jnp.asarray(w), "int8")
        qd.quant_matmul(jnp.asarray(x), qw)
        st0 = compilestats.snapshot()[kreg.QUANT_MATMUL_SURFACE]

        @jax.jit
        def outer(xv, qwv):
            return qd.quant_matmul(xv, qwv)
        outer(jnp.asarray(x), qw)   # tracer operands: no new surface rows
        st1 = compilestats.snapshot()[kreg.QUANT_MATMUL_SURFACE]
        assert st1["compiles"] == st0["compiles"]


# ---------------------------------------------------------------------------
# serving: quant_mode end to end (dense / paged / speculative)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt():
    from paddle_tpu.models import GPTForPretraining, gpt3_tiny
    paddle.seed(0)
    m = GPTForPretraining(gpt3_tiny())
    m.eval()
    return m


def _decode(gpt, **kw):
    from paddle_tpu.inference.serving import ServingEngine
    eng = ServingEngine(gpt, num_slots=2, chunk=4, max_seq_len=64, **kw)
    reqs = [eng.submit(list(range(3 + i, 10 + i)), max_new_tokens=8)
            for i in range(3)]
    eng.run()
    return eng, [list(r.tokens) for r in reqs]


def _agreement(a, b):
    n = d = 0
    for x, y in zip(a, b):
        for u, v in zip(x, y):
            d += 1
            n += int(u == v)
    return n / d


class TestQuantizedServing:
    def test_bad_mode_raises(self, gpt):
        from paddle_tpu.inference.serving import ServingEngine
        with pytest.raises(ValueError, match="quant_mode"):
            ServingEngine(gpt, num_slots=2, quant_mode="int4")

    def test_default_stays_bitwise_vs_generate(self, gpt):
        """quant_mode=None is the parity-critical path: greedy output
        bitwise-identical to generate(), untouched by this PR."""
        prompt = np.arange(3, 10, dtype="int32")[None, :]
        ids, _ = gpt.generate(paddle.to_tensor(prompt), max_new_tokens=8)
        ref = np.asarray(ids._value)[0].tolist()
        _, toks = _decode(gpt)
        assert toks[0] == ref

    def test_dense_agreement_and_gauge(self, gpt):
        _, base = _decode(gpt)
        eng_i8, i8 = _decode(gpt, quant_mode="int8")
        assert _agreement(base, i8) >= 0.99
        reg = paddle.observability.get_registry()
        g = reg.get("pt_serving_quant_bytes_saved")
        assert g is not None and g.value() > 0
        assert eng_i8.quant_mode == "int8"
        assert any(isinstance(v, qd.QuantizedWeight)
                   for v in eng_i8._pvals)
        _, f8 = _decode(gpt, quant_mode="fp8")
        assert _agreement(base, f8) >= 0.99

    def test_composes_with_paged_int8kv_and_spec(self, gpt):
        from paddle_tpu.inference.speculative import SpecConfig
        _, base = _decode(gpt)
        _, paged = _decode(gpt, kv_mode="paged", kv_dtype="int8",
                           num_pages=32, quant_mode="int8")
        assert _agreement(base, paged) >= 0.99
        eng, spec = _decode(gpt, spec_decode=SpecConfig(gamma=2),
                            quant_mode="fp8")
        assert _agreement(base, spec) >= 0.99
        # the draft model path stays unquantized by policy: the n-gram
        # drafter has no weights, but the engine's own pvals must carry
        # quantized containers
        assert any(isinstance(v, qd.QuantizedWeight) for v in eng._pvals)

    def test_refresh_weights_requantizes(self, gpt):
        eng, first = _decode(gpt, quant_mode="int8")
        eng.refresh_weights()
        assert any(isinstance(v, qd.QuantizedWeight)
                   for v in eng._pvals)
        reqs = [eng.submit(list(range(3 + i, 10 + i)), max_new_tokens=8)
                for i in range(3)]
        eng.run()
        assert [list(r.tokens) for r in reqs] == first


class TestTiedHeadQuant:
    """The GPT LM head is the tied vocab table (``tied_lm_head``): the
    quantization pass stores it TRANSPOSED — (H, V) with per-vocab
    channels — so one narrow copy serves both the decode head matmul
    (``quant_matmul``) and the input gather (``dequant_rows``)."""

    def test_dequant_rows_roundtrip_bound(self):
        rng = np.random.RandomState(2)
        table = rng.randn(40, 16).astype("f4")                # (V, H)
        qw = qd.quantize_weight(jnp.asarray(table).T, "int8")  # (H, V)
        assert qw.scale.shape == (40,)
        ids = [0, 7, 39, 7]
        rows = np.asarray(qd.dequant_rows(qw, jnp.asarray(ids)))
        assert rows.shape == (4, 16)
        # per-vocab-channel half-int8-step bound, like the (K, N) case
        bound = np.abs(table).max(axis=1)[ids, None] / 254.0
        assert (np.abs(rows - table[ids]) <= bound + 1e-7).all()

    def test_dequant_rows_batched_ids(self):
        rng = np.random.RandomState(3)
        table = rng.randn(12, 6).astype("f4")
        qw = qd.quantize_weight(jnp.asarray(table).T, "int8")
        out = np.asarray(qd.dequant_rows(qw, jnp.asarray([[1, 2], [3, 4]])))
        assert out.shape == (2, 2, 6)

    def test_engine_quantizes_tied_head_transposed(self, gpt):
        eng, _ = _decode(gpt, quant_mode="int8")
        V, H = (int(d) for d in gpt.tied_lm_head.shape)
        heads = [v for v in eng._pvals
                 if isinstance(v, qd.QuantizedWeight) and v.shape == (H, V)]
        assert len(heads) == 1 and heads[0].scale.shape == (V,)
        # the bytes-saved gauge books the head plus the Linears
        reg = paddle.observability.get_registry()
        saved = reg.get("pt_serving_quant_bytes_saved").value()
        assert saved > heads[0].bytes_saved() > 0


# ---------------------------------------------------------------------------
# hapi fp8 train pilot: delayed scaling, checkpoints, guardian chaos
# ---------------------------------------------------------------------------

def _train_model(amp_configs=None, seed=3):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m = paddle.Model(net, inputs=[InputSpec([None, 4], "float32", "x")],
                     labels=[InputSpec([None, 2], "float32", "y")])
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    m.prepare(opt, nn.MSELoss(), amp_configs=amp_configs)
    return m


def _train_batches(n=20, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(8, 4).astype("float32"),
             rng.randn(8, 2).astype("float32")) for _ in range(n)]


class _ArmAt(cbks_mod.Callback):
    def __init__(self, at_step, name, action):
        super().__init__()
        self.at_step, self.name, self.action = at_step, name, action

    def on_train_batch_end(self, step, logs=None):
        if step == self.at_step:
            failpoints.set_failpoint(self.name, self.action)


class TestFp8TrainPilot:
    def test_loss_parity_envelope(self):
        """The documented envelope: fp8 fake-quant training tracks the
        full-precision run within 5% relative loss on the regression
        model (measured ~2%)."""
        batches = _train_batches()

        def losses(m):
            out = []
            for x, y in batches:
                res = m.train_batch([x], [y])
                loss = res[0] if isinstance(res, (tuple, list)) else res
                while isinstance(loss, (tuple, list, np.ndarray)):
                    loss = loss[0]
                out.append(float(loss))
            return out

        base = losses(_train_model())
        f8 = losses(_train_model(amp_configs="fp8"))
        assert all(np.isfinite(f8))
        rel = [abs(a - b) / max(abs(a), 1e-6) for a, b in zip(base, f8)]
        assert max(rel) < 0.05, max(rel)

    def test_amax_state_populates_and_checkpoints(self):
        m = _train_model(amp_configs="fp8")
        batches = _train_batches(2)
        for x, y in batches:
            m.train_batch([x], [y])
        st = m._stepper
        amax = np.asarray(st.fp8_state)
        assert amax.shape == (len(st._fp8_idx),) and (amax > 0).all()
        sd = m.train_state_dict()
        assert "fp8" in sd
        np.testing.assert_array_equal(np.asarray(sd["fp8"]["amax"]), amax)
        # restore path: a scaled amax vector round-trips exactly
        m2 = _train_model(amp_configs="fp8")
        flat = {"model." + k: v._value
                for k, v in m2.network.state_dict().items()}
        flat["fp8.amax"] = amax * 2.0
        m2._restore_train_state(flat)
        np.testing.assert_allclose(np.asarray(m2._stepper.fp8_state),
                                   amax * 2.0)

    def test_accumulation_rejected(self):
        m = _train_model(amp_configs="fp8")
        x, y = _train_batches(1)[0]
        with pytest.raises(ValueError, match="accumulation"):
            m.train_batch([x], [y], update=False)

    def test_amp_dict_spelling_and_jit_requirement(self):
        m = _train_model(amp_configs={"fp8": True})
        assert m._stepper.fp8_matmul
        paddle.seed(3)
        net = nn.Linear(4, 2)
        mm = paddle.Model(net,
                          inputs=[InputSpec([None, 4], "float32", "x")],
                          labels=[InputSpec([None, 2], "float32", "y")])
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        with pytest.raises(ValueError, match="jit"):
            mm.prepare(opt, nn.MSELoss(), amp_configs="fp8", jit=False)

    @pytest.mark.chaos
    @pytest.mark.guardian
    def test_guardian_poison_chaos_keeps_amax_finite(self):
        """A poisoned batch under fp8 reaches the numeric sentinel (the
        saturating cast clips but propagates nonfinites), the step
        skips, and the delayed-scaling state stays finite."""
        m = _train_model(amp_configs="fp8")
        cfg = guardian.GuardianConfig(skip_limit=3, ckpt_root=None,
                                      loss_spike=False)
        m.fit(_train_batches(12), epochs=1, verbose=0, guardian=cfg,
              callbacks=[_ArmAt(3, "guardian.poison_batch", "skip*1")])
        skips = guardian.events("skip_step")
        assert len(skips) == 1 and skips[0]["reason"] == "nonfinite"
        assert np.isfinite(np.asarray(m._stepper.fp8_state)).all()
        for k, v in m.network.state_dict().items():
            assert np.isfinite(np.asarray(v._value)).all(), k
