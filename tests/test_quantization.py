"""paddle.quantization: observers, quanters, QAT/PTQ drivers, convert.

Reference analogues: test/quantization/test_quant_aware*.py,
test_ptq.py, test_observers.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    QuantConfig, QAT, PTQ, quanters, observers,
    QuantedLinear, ConvertedQuantedLinear)


def _mlp():
    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.act = nn.ReLU()
            self.fc2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))
    return MLP()


class TestObservers:
    def test_absmax(self):
        ob = observers.AbsmaxObserver()
        ob(paddle.to_tensor(np.array([1.0, -3.0], "float32")))
        ob(paddle.to_tensor(np.array([2.0, -0.5], "float32")))
        assert ob.scales() == pytest.approx(3.0)

    def test_avg(self):
        ob = observers.AVGObserver()
        ob(paddle.to_tensor(np.array([2.0], "float32")))
        ob(paddle.to_tensor(np.array([4.0], "float32")))
        assert ob.scales() == pytest.approx(3.0)

    def test_hist(self):
        rng = np.random.RandomState(0)
        ob = observers.HistObserver(percent=1.0)
        data = rng.uniform(-1, 1, 4096).astype("float32")
        ob(paddle.to_tensor(data))
        assert ob.scales() == pytest.approx(np.abs(data).max(), rel=1e-2)

    def test_observer_is_identity(self):
        ob = observers.AbsmaxObserver()
        x = np.array([1.0, -2.0], "float32")
        out = ob(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), x)


class TestQuanters:
    def test_fake_quant_values(self):
        q = quanters.FakeQuanterWithAbsMaxObserver()
        q.eval()
        q._scale_value = 1.0
        x = np.array([0.5, -1.0, 0.126], "float32")
        out = q(paddle.to_tensor(x)).numpy()
        ref = np.round(x * 127) / 127
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_ste_gradient(self):
        q = quanters.FakeQuanterWithAbsMaxObserver()
        q.train()
        x = paddle.to_tensor(np.array([0.3, -0.7], "float32"))
        x.stop_gradient = False
        out = q(x)
        paddle.sum(out).backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(2), atol=1e-6)

    def test_channelwise(self):
        q = quanters.FakeQuanterChannelWiseAbsMaxObserver(quant_axis=1)
        w = np.array([[1.0, 10.0], [-0.5, -20.0]], "float32")
        out = q(paddle.to_tensor(w)).numpy()
        # per-column scales: 1.0 and 20.0
        ref0 = np.round(w[:, 0] / 1.0 * 127) / 127 * 1.0
        ref1 = np.round(w[:, 1] / 20.0 * 127) / 127 * 20.0
        np.testing.assert_allclose(out[:, 0], ref0, atol=1e-5)
        np.testing.assert_allclose(out[:, 1], ref1, atol=1e-4)
        np.testing.assert_allclose(q.scales(), [1.0, 20.0])


class TestQAT:
    def test_quantize_swaps_layers(self):
        cfg = QuantConfig(
            activation=lambda: quanters.FakeQuanterWithAbsMaxObserver(),
            weight=lambda: quanters.FakeQuanterChannelWiseAbsMaxObserver(
                quant_axis=1))
        model = _mlp()
        qat = QAT(cfg)
        qmodel = qat.quantize(model)
        assert isinstance(qmodel.fc1, QuantedLinear)
        assert isinstance(qmodel.fc2, QuantedLinear)

    def test_qat_trains_and_stays_close(self):
        rng = np.random.RandomState(1)
        x = rng.randn(32, 8).astype("float32")
        model = _mlp()
        ref = model(paddle.to_tensor(x)).numpy()
        cfg = QuantConfig(
            activation=lambda: quanters.FakeQuanterWithAbsMaxObserver(),
            weight=lambda: quanters.FakeQuanterChannelWiseAbsMaxObserver(
                quant_axis=1))
        qmodel = QAT(cfg).quantize(model)
        qmodel.train()
        for _ in range(20):   # moving-average scale warm-up
            out = qmodel(paddle.to_tensor(x))
        # fake-quant output close to float output (8-bit ⇒ ~1% scale err)
        err = np.abs(out.numpy() - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.1
        # gradients flow to weights through fake-quant
        loss = paddle.mean(out * out)
        loss.backward()
        assert qmodel.fc1.weight.grad is not None
        g = np.asarray(qmodel.fc1.weight.grad.numpy())
        assert np.abs(g).max() > 0

    def test_type_config(self):
        cfg = QuantConfig()
        cfg.add_type_config(
            nn.Linear,
            activation=lambda: quanters.FakeQuanterWithAbsMaxObserver())
        model = _mlp()
        qmodel = QAT(cfg).quantize(model)
        assert isinstance(qmodel.fc1, QuantedLinear)

    def test_convert_int8(self):
        rng = np.random.RandomState(2)
        x = rng.randn(16, 8).astype("float32")
        model = _mlp()
        cfg = QuantConfig(
            activation=None,
            weight=lambda: quanters.FakeQuanterChannelWiseAbsMaxObserver(
                quant_axis=1))
        qmodel = QAT(cfg).quantize(model)
        qmodel.train()
        qout = qmodel(paddle.to_tensor(x)).numpy()
        dmodel = QAT(cfg).convert(qmodel)
        assert isinstance(dmodel.fc1, ConvertedQuantedLinear)
        assert dmodel.fc1.w_int.dtype == np.int8
        dout = dmodel(paddle.to_tensor(x)).numpy()
        # weight-only int8 deploy ≈ fake-quant QAT output
        np.testing.assert_allclose(dout, qout, rtol=1e-2, atol=5e-2)


class TestPTQ:
    def test_ptq_calibrate_convert(self):
        rng = np.random.RandomState(3)
        xs = [rng.randn(16, 8).astype("float32") for _ in range(4)]
        model = _mlp()
        ref = model(paddle.to_tensor(xs[0])).numpy()
        cfg = QuantConfig(
            activation=lambda: observers.AbsmaxObserver(),
            weight=None)
        ptq = PTQ(cfg)
        qmodel = ptq.quantize(model)
        qmodel.eval()
        for x in xs:                      # calibration passes
            qmodel(paddle.to_tensor(x))
        assert qmodel.fc1.activation_quanter.scales() is not None
        dmodel = ptq.convert(qmodel)
        out = dmodel(paddle.to_tensor(xs[0])).numpy()
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.15   # int8 act+weight quantization error bound

    def test_quantize_not_inplace(self):
        model = _mlp()
        cfg = QuantConfig(
            activation=lambda: quanters.FakeQuanterWithAbsMaxObserver())
        qmodel = QAT(cfg).quantize(model)
        assert isinstance(qmodel.fc1, QuantedLinear)
        assert isinstance(model.fc1, nn.Linear)   # original untouched
        qmodel2 = QAT(cfg).quantize(model, inplace=True)
        assert qmodel2 is model
        assert isinstance(model.fc1, QuantedLinear)

    def test_convert_channelwise_axis0_falls_back(self):
        # quant_axis=0 scales are per-input-row; convert must re-derive
        # per-output-channel scales instead of crashing/mis-scaling
        rng = np.random.RandomState(4)
        x = rng.randn(8, 8).astype("float32")
        model = _mlp()
        cfg = QuantConfig(
            weight=lambda: quanters.FakeQuanterChannelWiseAbsMaxObserver(
                quant_axis=0))
        qmodel = QAT(cfg).quantize(model)
        qmodel.train()
        qmodel(paddle.to_tensor(x))
        dmodel = QAT(cfg).convert(qmodel)
        ref = model(paddle.to_tensor(x)).numpy()
        out = dmodel(paddle.to_tensor(x)).numpy()
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.1

    def test_convert_square_matrix_axis0(self):
        # square weight + quant_axis=0: size alone can't disambiguate the
        # axis; convert must consult quant_axis() and re-derive
        rng = np.random.RandomState(5)
        x = rng.randn(4, 16).astype("float32")

        class Sq(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(16, 16)

            def forward(self, x):
                return self.fc(x)

        model = Sq()
        ref = model(paddle.to_tensor(x)).numpy()
        cfg = QuantConfig(
            weight=lambda: quanters.FakeQuanterChannelWiseAbsMaxObserver(
                quant_axis=0))
        qmodel = QAT(cfg).quantize(model)
        qmodel.train()
        qmodel(paddle.to_tensor(x))
        dmodel = QAT(cfg).convert(qmodel)
        out = dmodel(paddle.to_tensor(x)).numpy()
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.1

    def test_ptq_conv_convert_deterministic(self):
        # converted conv layers must hold frozen scales (no live observers)
        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 4, 3, padding=1)

            def forward(self, x):
                return self.conv(x)

        rng = np.random.RandomState(6)
        model = ConvNet()
        cfg = QuantConfig(
            activation=lambda: observers.AbsmaxObserver())
        ptq = PTQ(cfg)
        qmodel = ptq.quantize(model)
        qmodel.eval()
        qmodel(paddle.to_tensor(rng.randn(1, 3, 8, 8).astype("float32")))
        dmodel = ptq.convert(qmodel)
        from paddle_tpu.quantization import ConvertedQuantedConv2D
        assert isinstance(dmodel.conv, ConvertedQuantedConv2D)
        # outputs identical across calls even with larger-range inputs
        x1 = rng.randn(1, 3, 8, 8).astype("float32") * 10
        o1 = dmodel(paddle.to_tensor(x1)).numpy()
        dmodel(paddle.to_tensor(x1 * 5))
        o2 = dmodel(paddle.to_tensor(x1)).numpy()
        np.testing.assert_array_equal(o1, o2)

    def test_hist_rebin_on_widening_range(self):
        ob = observers.HistObserver(percent=1.0)
        ob(paddle.to_tensor(np.linspace(-1, 1, 1000).astype("float32")))
        ob(paddle.to_tensor(np.linspace(-2, 2, 1000).astype("float32")))
        # all mass within [0,2]; percentile-1.0 scale ≈ 2, and the rebinned
        # first batch must not be collapsed into the top bin
        assert ob.scales() == pytest.approx(2.0, rel=2e-2)
        h = ob._hist
        assert h[-1] < h.sum() * 0.1   # top bin holds a small fraction

    def test_int8_dot_path_used(self):
        # act_scale present → ConvertedQuantedLinear runs int8 dot_general
        layer = ConvertedQuantedLinear(
            np.array([[127, 0], [0, 127]], np.int8),
            np.array([1.0, 1.0], "float32"),
            None, act_scale=1.0)
        x = np.array([[0.5, -0.25]], "float32")
        out = layer(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, [[0.5, -0.252]], atol=5e-3)


def test_fp8_linear_deploy_path():
    """FP8Linear (VERDICT r3 #5): weight-only e4m3 linear matches the
    dense layer within fp8 quantization error, and fp8_quantize swaps
    every nn.Linear in a model."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.quantization import FP8Linear, fp8_quantize

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 32))
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(8, 64).astype("f4"))
    ref = net(x).numpy()

    qnet = fp8_quantize(net)                     # deep-copied
    assert isinstance(qnet[0], FP8Linear) and isinstance(qnet[2], FP8Linear)
    assert qnet[0].w_fp8.dtype == jnp.float8_e4m3fn
    assert isinstance(net[0], nn.Linear)         # original untouched
    out = qnet(x).numpy()
    # e4m3 has ~2 decimal digits; layered error stays within a few %
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.06, rel
    # weight HBM footprint halves vs bf16
    assert qnet[0].w_fp8.dtype.itemsize * 2 == jnp.dtype(jnp.bfloat16).itemsize


def test_weight_only_int4_roundtrip_and_linear():
    """r5: weight_only_int4 — nibble-packed storage (K/2, N), quantize/
    dequantize round trip within int4 tolerance, and weight_only_linear
    matches the dequantized matmul exactly."""
    from paddle_tpu.nn.quant import (weight_quantize, weight_dequantize,
                                     weight_only_linear)
    rs = np.random.RandomState(0)
    w = rs.randn(16, 8).astype("f4")
    q, s = weight_quantize(paddle.to_tensor(w), algo="weight_only_int4")
    assert tuple(q.shape) == (8, 8)          # two K rows per byte
    assert str(q._value.dtype) == "int8"
    wd = weight_dequantize(q, s, algo="weight_only_int4")
    # int4 has 15 levels: |err| <= scale/2 elementwise
    err = np.abs(np.asarray(wd._value) - w)
    assert (err <= np.asarray(s._value)[None, :] * 0.5 + 1e-6).all()

    x = rs.randn(3, 16).astype("f4")
    out = weight_only_linear(paddle.to_tensor(x), q, weight_scale=s,
                             weight_dtype="int4")
    ref = x @ np.asarray(wd._value)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5,
                               atol=1e-5)
    # odd K is rejected with a clear message
    with pytest.raises(ValueError, match="even"):
        weight_quantize(paddle.to_tensor(rs.randn(15, 8).astype("f4")),
                        algo="weight_only_int4")


def test_weight_only_int4_grad_wrt_activation():
    from paddle_tpu.nn.quant import weight_quantize, weight_only_linear
    rs = np.random.RandomState(1)
    w = rs.randn(8, 6).astype("f4")
    q, s = weight_quantize(paddle.to_tensor(w), algo="weight_only_int4")
    x = paddle.to_tensor(rs.randn(2, 8).astype("f4"),
                         stop_gradient=False)
    out = weight_only_linear(x, q, weight_scale=s, weight_dtype="int4")
    out.sum().backward()
    assert np.isfinite(x.grad.numpy()).all()


def test_weight_only_quantize_module_swap():
    """weight_only_quantize: int8/int4 sibling of fp8_quantize — swaps
    every nn.Linear for a WeightOnlyLinear whose output matches the
    dense layer within quantization error; state rides as buffers."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.quantization import (WeightOnlyLinear,
                                         weight_only_quantize)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 32))
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(8, 64).astype("f4"))
    ref = net(x).numpy()

    for algo, tol in (("weight_only_int8", 0.03), ("weight_only_int4",
                                                   0.25)):
        qnet = weight_only_quantize(net, algo=algo)
        assert isinstance(qnet[0], WeightOnlyLinear)
        assert isinstance(net[0], nn.Linear)     # original untouched
        out = qnet(x).numpy()
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < tol, (algo, rel)
        # quantized weights are buffers (in state_dict, not parameters)
        assert "0.qweight" in qnet.state_dict()
        assert all("qweight" not in n for n, _ in qnet.named_parameters())
    # int4 packs two K rows per byte
    q4 = weight_only_quantize(net, algo="weight_only_int4")
    assert tuple(q4[0].qweight.shape) == (32, 128)
    assert q4[0].qweight.dtype == jnp.int8


def test_weight_only_quantized_model_generates():
    """generate() on an int8/int4 weight-only model (packed weights ride
    as buffers through the compiled decode)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForPretraining, gpt3_tiny
    from paddle_tpu.quantization import weight_only_quantize

    paddle.seed(0)
    net = GPTForPretraining(gpt3_tiny())
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 1024, (2, 5)).astype("int32"))
    for algo in ("weight_only_int8", "weight_only_int4"):
        qnet = weight_only_quantize(net, algo=algo)
        out, sc = qnet.generate(ids, max_new_tokens=4)
        toks = np.asarray(out._value)
        assert toks.shape == (2, 4)
        assert toks.min() >= 0 and toks.max() < 1024
        assert np.all(np.isfinite(np.asarray(sc._value)))
