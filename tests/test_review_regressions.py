"""Regression tests for code-review findings (round 1)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.autograd import call_op


def test_backward_through_mixed_stop_gradient_consumer():
    # producer feeds both a stop_gradient-cut edge and a live edge
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    t = x * 2.0
    a = t * 3.0          # live consumer
    t_cut = t.detach()
    b = t_cut * 5.0      # consumer through a cut edge
    (a.sum() + b.sum()).backward()
    # only the live path contributes: d/dx sum(6x) = 6
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])


def test_mode_longest_earlier_run():
    v, idx = paddle.mode(paddle.to_tensor([1.0, 3.0, 1.0, 2.0, 1.0, 3.0]))
    assert float(v) == 1.0
    assert float(paddle.to_tensor([1.0, 3.0, 1.0, 2.0, 1.0, 3.0])
                 .numpy()[int(idx)]) == 1.0


def test_mode_axis():
    x = paddle.to_tensor(np.array([[1., 1., 2.], [3., 2., 2.]]))
    v, idx = paddle.mode(x, axis=-1)
    np.testing.assert_allclose(v.numpy(), [1.0, 2.0])


def test_maxpool_ceil_mode():
    x = paddle.to_tensor(np.arange(25, dtype="float32").reshape(1, 1, 5, 5))
    out = F.max_pool2d(x, 2, 2, ceil_mode=True)
    assert out.shape == [1, 1, 3, 3]
    np.testing.assert_allclose(out.numpy()[0, 0, 2], [21, 23, 24])
    out_floor = F.max_pool2d(x, 2, 2, ceil_mode=False)
    assert out_floor.shape == [1, 1, 2, 2]


def test_avgpool_ceil_mode_partial_window():
    x = paddle.to_tensor(np.ones((1, 1, 5, 5), dtype="float32"))
    out = F.avg_pool2d(x, 2, 2, ceil_mode=True)
    assert out.shape == [1, 1, 3, 3]
    # partial windows average only real elements
    np.testing.assert_allclose(out.numpy()[0, 0], np.ones((3, 3)))


def test_grad_scaler_decreases_on_inf():
    from paddle_tpu.amp import GradScaler
    w = paddle.to_tensor([1.0], stop_gradient=False)
    opt = paddle.optimizer.SGD(0.1, parameters=[w])
    scaler = GradScaler(init_loss_scaling=1024.,
                        decr_every_n_nan_or_inf=2, incr_every_n_steps=1000)
    for _ in range(4):  # 4 inf steps with the documented step+update loop
        w._grad = paddle.to_tensor([float("inf")])._value
        scaler.step(opt)
        scaler.update()
    assert scaler._scale < 1024.0, scaler._scale
    np.testing.assert_allclose(w.numpy(), [1.0])  # never stepped on inf


def test_adamw_apply_decay_param_fun_eager():
    from paddle_tpu.optimizer import AdamW
    w = paddle.to_tensor([1.0], stop_gradient=False)
    b = paddle.to_tensor([1.0], stop_gradient=False)
    b.name = "layer.bias"
    opt = AdamW(learning_rate=0.0, parameters=[w, b], weight_decay=0.5,
                apply_decay_param_fun=lambda n: "bias" not in n)
    w._grad = paddle.zeros([1])._value
    b._grad = paddle.zeros([1])._value
    opt.step()
    # lr=0 → adam update is 0; only decay could change values, and decay
    # is gated by the fun.  With lr=0 decay is also 0 — use lr>0 instead.
    opt2 = AdamW(learning_rate=0.1, parameters=[w, b], weight_decay=0.5,
                 apply_decay_param_fun=lambda n: "bias" not in n)
    w._grad = paddle.zeros([1])._value
    b._grad = paddle.zeros([1])._value
    w0, b0 = float(w.numpy()[0]), float(b.numpy()[0])
    opt2.step()
    assert float(w.numpy()[0]) < w0      # decayed
    np.testing.assert_allclose(b.numpy(), [b0], rtol=1e-6)  # excluded


def test_gradient_accumulation_jit():
    from paddle_tpu.static import InputSpec
    xs = [np.random.rand(4, 8).astype("float32") for _ in range(2)]
    ys = [np.random.randint(0, 3, (4, 1)).astype("int64") for _ in range(2)]

    def build():
        paddle.seed(3)
        net = nn.Linear(8, 3)
        m = paddle.Model(net, inputs=[InputSpec([None, 8], "float32")],
                         labels=[InputSpec([None, 1], "int64")])
        m.prepare(paddle.optimizer.SGD(0.5, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
        return m, net

    # accumulate 2 micro-batches == one step on the concatenated batch
    m1, n1 = build()
    m1.train_batch([xs[0]], [ys[0]], update=False)
    m1.train_batch([xs[1]], [ys[1]], update=True)

    m2, n2 = build()
    xcat = np.concatenate(xs)
    ycat = np.concatenate(ys)
    m2.train_batch([xcat], [ycat], update=True)
    np.testing.assert_allclose(n1.weight.numpy(), n2.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_batchnorm_running_stats_single_source():
    bn = nn.BatchNorm1D(4)
    x = paddle.randn([16, 4])
    bn.train()
    y = bn(x)
    m = x.numpy().mean(0)
    v = x.numpy().var(0, ddof=1)
    np.testing.assert_allclose(bn._mean.numpy(), 0.1 * m, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(bn._variance.numpy(), 0.9 + 0.1 * v,
                               rtol=1e-4, atol=1e-5)


def test_eager_backward_loop_warns_once():
    """Advisor r2 / VERDICT #9: a hot loop of un-jitted .backward() calls
    should emit ONE performance warning (eager is ~2.7x slower)."""
    import warnings
    from paddle_tpu.framework import autograd as ag
    saved = ag._EAGER_BACKWARD_CALLS
    try:
        ag._EAGER_BACKWARD_CALLS = 0
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            for _ in range(ag._EAGER_LOOP_WARN_AT + 4):
                x = paddle.to_tensor([1.0], stop_gradient=False)
                (x * 2.0).sum().backward()
        msgs = [w for w in rec if "eagerly" in str(w.message)]
        assert len(msgs) == 1, f"expected exactly one warning, got {len(msgs)}"
    finally:
        ag._EAGER_BACKWARD_CALLS = saved


def test_conv_amp_bias_not_promoting_output():
    """Advisor r2: O1 autocast must cast conv bias too, else a fp32 bias
    promotes the conv output back to fp32."""
    import paddle_tpu.amp as amp
    x = paddle.randn([1, 3, 8, 8])
    w = paddle.randn([4, 3, 3, 3])
    b = paddle.randn([4])
    with amp.auto_cast(level="O1"):
        y = F.conv2d(x, w, bias=b)
        assert str(y.dtype).endswith("bfloat16"), y.dtype
        yt = F.conv2d_transpose(x, paddle.randn([3, 4, 3, 3]), bias=b)
        assert str(yt.dtype).endswith("bfloat16"), yt.dtype
        y1 = F.conv1d(paddle.randn([1, 3, 8]), paddle.randn([4, 3, 3]),
                      bias=b)
        assert str(y1.dtype).endswith("bfloat16"), y1.dtype


def test_static_layer_cache_not_keyed_by_recycled_id():
    """Advisor r2: _LAYER_CACHE must die with its Program (weakref key),
    not survive via a recycled id()."""
    import gc
    import paddle_tpu.static as static
    from paddle_tpu.static import nn as snn
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 8], "float32")
            snn.fc(x, 16)
        assert prog in snn._LAYER_CACHE
        del prog, x
        gc.collect()
        # all cache entries must belong to live programs
        for p in list(snn._LAYER_CACHE.keys()):
            assert p is not None
    finally:
        paddle.disable_static()
