"""RNN layers vs torch goldens (reference tests use numpy rnn_numpy.py
goldens; torch-cpu is our independent implementation to check against)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _copy_lstm_weights(pt, our, layer=0):
    our.weight_ih.set_value(pt.weight_ih_l0.detach().numpy())
    our.weight_hh.set_value(pt.weight_hh_l0.detach().numpy())
    our.bias_ih.set_value(pt.bias_ih_l0.detach().numpy())
    our.bias_hh.set_value(pt.bias_hh_l0.detach().numpy())


def test_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(0)
    x = rng.randn(3, 7, 8).astype("f4")
    pt = torch.nn.LSTM(8, 16, batch_first=True)
    our = nn.LSTM(8, 16)
    _copy_lstm_weights(pt, our.cells_fw[0])
    with torch.no_grad():
        ref, (h_ref, c_ref) = pt(torch.tensor(x))
    out, (h, c) = our(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(h.numpy(), h_ref.numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(c.numpy(), c_ref.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_gru_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    x = rng.randn(2, 5, 4).astype("f4")
    pt = torch.nn.GRU(4, 6, batch_first=True)
    our = nn.GRU(4, 6)
    c = our.cells_fw[0]
    c.weight_ih.set_value(pt.weight_ih_l0.detach().numpy())
    c.weight_hh.set_value(pt.weight_hh_l0.detach().numpy())
    c.bias_ih.set_value(pt.bias_ih_l0.detach().numpy())
    c.bias_hh.set_value(pt.bias_hh_l0.detach().numpy())
    with torch.no_grad():
        ref, h_ref = pt(torch.tensor(x))
    out, h = our(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_bidirectional_and_grad():
    paddle.seed(0)
    net = nn.LSTM(8, 16, num_layers=2, direction="bidirect")
    x = paddle.to_tensor(np.random.rand(4, 5, 8).astype("f4"),
                         stop_gradient=False)
    y, (h, c) = net(x)
    assert y.shape == [4, 5, 32]
    assert h.shape == [4, 4, 16]  # num_layers * num_directions
    y.mean().backward()
    assert x.grad is not None
    assert net.cells_fw[0].weight_ih.grad is not None
