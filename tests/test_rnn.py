"""RNN layers vs torch goldens (reference tests use numpy rnn_numpy.py
goldens; torch-cpu is our independent implementation to check against)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _copy_lstm_weights(pt, our, layer=0):
    our.weight_ih.set_value(pt.weight_ih_l0.detach().numpy())
    our.weight_hh.set_value(pt.weight_hh_l0.detach().numpy())
    our.bias_ih.set_value(pt.bias_ih_l0.detach().numpy())
    our.bias_hh.set_value(pt.bias_hh_l0.detach().numpy())


def test_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(0)
    x = rng.randn(3, 7, 8).astype("f4")
    pt = torch.nn.LSTM(8, 16, batch_first=True)
    our = nn.LSTM(8, 16)
    _copy_lstm_weights(pt, our.cells_fw[0])
    with torch.no_grad():
        ref, (h_ref, c_ref) = pt(torch.tensor(x))
    out, (h, c) = our(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(h.numpy(), h_ref.numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(c.numpy(), c_ref.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_gru_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    x = rng.randn(2, 5, 4).astype("f4")
    pt = torch.nn.GRU(4, 6, batch_first=True)
    our = nn.GRU(4, 6)
    c = our.cells_fw[0]
    c.weight_ih.set_value(pt.weight_ih_l0.detach().numpy())
    c.weight_hh.set_value(pt.weight_hh_l0.detach().numpy())
    c.bias_ih.set_value(pt.bias_ih_l0.detach().numpy())
    c.bias_hh.set_value(pt.bias_hh_l0.detach().numpy())
    with torch.no_grad():
        ref, h_ref = pt(torch.tensor(x))
    out, h = our(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_bidirectional_and_grad():
    paddle.seed(0)
    net = nn.LSTM(8, 16, num_layers=2, direction="bidirect")
    x = paddle.to_tensor(np.random.rand(4, 5, 8).astype("f4"),
                         stop_gradient=False)
    y, (h, c) = net(x)
    assert y.shape == [4, 5, 32]
    assert h.shape == [4, 4, 16]  # num_layers * num_directions
    y.mean().backward()
    assert x.grad is not None
    assert net.cells_fw[0].weight_ih.grad is not None


def test_rnn_initial_states_and_sequence_length():
    """Round-4: _scan_cell honors warm-start states and padded-batch
    sequence_length (final state from last VALID step, outputs past
    length zeroed, reverse flips only the valid prefix)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    paddle.seed(0)
    cell = nn.GRUCell(3, 5)
    rnn = nn.RNN(cell)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 6, 3).astype("f4"))
    h0 = paddle.to_tensor(rng.randn(2, 5).astype("f4"))
    out0, hT0 = rnn(x)
    out1, hT1 = rnn(x, h0)
    assert not np.allclose(np.asarray(out0._value),
                           np.asarray(out1._value)), \
        "initial_states must change the result"
    # warm start == manually stepping the cell
    h = h0
    for t in range(6):
        _, h = cell(x[:, t, :], h)
    np.testing.assert_allclose(np.asarray(hT1._value),
                               np.asarray(h._value), rtol=1e-5,
                               atol=1e-6)

    # sequence_length: row 1 has only 3 valid steps
    lens = paddle.to_tensor(np.asarray([6, 3], "i4"))
    out2, hT2 = rnn(x, None, lens)
    # outputs past the length are zero
    np.testing.assert_allclose(np.asarray(out2._value)[1, 3:], 0.0)
    # final state of row 1 equals the full-run state at t=2
    np.testing.assert_allclose(np.asarray(hT2._value)[1],
                               np.asarray(out0._value)[1, 2], rtol=1e-5,
                               atol=1e-6)

    # reverse with lengths: valid prefix flipped, padding stays zero
    rrev = nn.RNN(nn.GRUCell(3, 5), is_reverse=True)
    outr, _ = rrev(x, None, lens)
    assert np.allclose(np.asarray(outr._value)[1, 3:], 0.0)
    assert not np.allclose(np.asarray(outr._value)[1, :3], 0.0)


def test_birnn_states_and_lengths_flow():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    paddle.seed(1)
    bi = nn.BiRNN(nn.GRUCell(3, 4), nn.GRUCell(3, 4))
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(2, 5, 3).astype("f4"))
    st = (paddle.to_tensor(rng.randn(2, 4).astype("f4")),
          paddle.to_tensor(rng.randn(2, 4).astype("f4")))
    out_a, _ = bi(x)
    out_b, _ = bi(x, st)
    assert not np.allclose(np.asarray(out_a._value),
                           np.asarray(out_b._value))
    lens = paddle.to_tensor(np.asarray([5, 2], "i4"))
    out_c, _ = bi(x, None, lens)
    np.testing.assert_allclose(np.asarray(out_c._value)[1, 2:], 0.0)
