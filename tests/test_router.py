"""Multi-replica serving fleet (inference/router.py): bitwise parity
through the router, prefix-affinity + least-loaded routing, SLO-aware
priority scheduling (ordering, aging, shed), replica lifecycle
(crash-drain-requeue chaos, scale-down, autoscale hints), the
route-span tracing contract, and the concurrency/host-sync lint
self-check on the router's locked regions.

The parity tests are the real check: whatever replica/slot a request
lands on — including after a mid-decode replica crash — greedy output
must match ``generate()`` token for token (resume-by-recompute)."""
import json
import threading

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.framework import failpoints, guardian
from paddle_tpu.inference import kvcache
from paddle_tpu.inference.router import ServingFleet
from paddle_tpu.observability import tracing, report, timeline
from paddle_tpu.models import GPTForPretraining, gpt3_tiny

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    return GPTForPretraining(gpt3_tiny())


@pytest.fixture(autouse=True)
def _clean():
    obs.get_registry().reset()
    tracing.reset()
    guardian.clear_events()
    failpoints.clear()
    yield
    failpoints.clear()


def _gen(gpt, prompt, n):
    ids, _ = gpt.generate(paddle.to_tensor(prompt[None, :]),
                          max_new_tokens=n)
    return np.asarray(ids._value)[0]


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 1024, (n,)).astype("int32") for n in lens]


@pytest.fixture(scope="module")
def fleet2(gpt):
    """Shared 2-replica dense fleet (compiles once per module)."""
    return ServingFleet(gpt, num_replicas=2, num_slots=2, chunk=4,
                        prefill_buckets=(8, 16))


class TestFleetParity:
    def test_serial_bitwise_and_balanced(self, gpt, fleet2):
        """Round-robin serial fleet: every request bitwise == its own
        generate() run, and the load balancer uses both replicas."""
        fleet2.reset()
        prompts = _prompts(1, (5, 11, 8, 3, 7, 9))
        refs = [_gen(gpt, p, 6) for p in prompts]
        reqs = [fleet2.submit(p, 6) for p in prompts]
        done = fleet2.run(threads=False, timeout=120)
        assert [r.req_id for r in done] == [r.req_id for r in reqs]
        for r, ref in zip(done, refs):
            np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                          ref)
        assert {r.replica for r in done} == {0, 1}
        assert all(r.route_reason in ("affinity", "least_loaded")
                   for r in done)

    def test_threaded_bitwise(self, gpt, fleet2):
        """Worker-thread mode: scheduling is nondeterministic, output
        must not be."""
        fleet2.reset()
        prompts = _prompts(2, (5, 11, 8, 3))
        refs = [_gen(gpt, p, 6) for p in prompts]
        reqs = [fleet2.submit(p, 6) for p in prompts]
        fleet2.run(threads=True, timeout=120)
        for r, ref in zip(reqs, refs):
            np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                          ref)

    def test_submit_validates_like_engine(self, fleet2):
        """A structurally impossible request raises at submit() —
        never silently surfaces later as an asynchronous 'shed'."""
        with pytest.raises(ValueError, match="largest"):
            fleet2.submit(np.arange(100, dtype=np.int32), 4)
        with pytest.raises(ValueError, match="max_seq_len"):
            fleet2.submit(np.arange(10, dtype=np.int32), 1000)
        with pytest.raises(ValueError, match="empty prompt"):
            fleet2.submit(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError, match="priority"):
            fleet2.submit(np.arange(5, dtype=np.int32), 4,
                          priority="vip")

    def test_submit_is_thread_safe(self, fleet2):
        fleet2.reset()
        prompts = _prompts(3, (5,)) * 5

        def burst():
            for p in prompts:
                fleet2.submit(p, 2)

        ts = [threading.Thread(target=burst) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        done = fleet2.run(threads=False, timeout=120)
        assert len(done) == 20
        assert len({r.req_id for r in done}) == 20
        assert all(r.finish_reason == "budget" for r in done)


class TestRouting:
    def test_affinity_key_helper(self):
        rng = np.random.RandomState(0)
        sys = rng.randint(0, 1024, (32,)).astype("int32")
        a = np.concatenate([sys, rng.randint(0, 1024, (5,)).astype("int32")])
        b = np.concatenate([sys, rng.randint(0, 1024, (9,)).astype("int32")])
        ka = kvcache.prefix_affinity_key(a, 8, max_pages=4)
        kb = kvcache.prefix_affinity_key(b, 8, max_pages=4)
        assert ka == kb is not None
        other = rng.randint(0, 1024, (40,)).astype("int32")
        assert kvcache.prefix_affinity_key(other, 8, 4) != ka
        # no full page -> no key (route by load)
        assert kvcache.prefix_affinity_key(sys[:7], 8, 4) is None
        # the key is the chained page digest of the capped prefix: it
        # must equal the prefix cache's key for the same pages
        assert bytes.fromhex(ka) == \
            kvcache.chained_page_digests(a[:32], 8)[3]

    def test_rebalance_steals_parked_work(self, gpt, fleet2):
        """An idle replica steals queued-but-unadmitted work off the
        deepest replica queue (the straggler fix); the FCFS head of the
        deep queue never moves."""
        fleet2.reset()
        for p in _prompts(16, (5,) * 6):
            fleet2.submit(p, 3)
        fleet2._dispatch()               # parks 2 on each + 2 backpressured
        rep0 = fleet2.replicas[0].engine.scheduler
        rep1 = fleet2.replicas[1].engine.scheduler
        # drain replica 1's queue so it sits idle with free slots while
        # replica 0 still has parked work
        for r in rep1.drain_queue():
            rep0.enqueue(r)
        head = rep0._queue[0].req_id
        fleet2._rebalance()
        assert fleet2.stats["rebalanced"] >= 1
        assert rep0._queue[0].req_id == head     # FCFS head untouched
        assert rep1.queue_depth >= 1
        done = fleet2.run(threads=False, timeout=120)
        assert all(r.finish_reason == "budget" for r in done)
        reasons = {r.route_reason for r in done}
        assert "rebalance" in reasons

    def test_prefix_affinity_pins_shared_prompts(self, gpt):
        """Requests sharing a system prompt land on one replica (warm
        prefix cache); unrelated prompts spread by load."""
        rng = np.random.RandomState(3)
        sys = rng.randint(0, 1024, (32,)).astype("int32")
        shared = [np.concatenate([sys, rng.randint(0, 1024, (k,))
                                  .astype("int32")]) for k in (3, 5, 7)]
        others = [rng.randint(0, 1024, (9,)).astype("int32")
                  for _ in range(3)]
        fleet = ServingFleet(gpt, num_replicas=2, num_slots=2, chunk=4,
                             kv_mode="paged", page_size=8,
                             prefill_buckets=(8, 16, 32, 64),
                             max_seq_len=128, affinity_pages=4)
        reqs = [fleet.submit(p, 4) for p in shared + others]
        fleet.run(threads=False, timeout=120)
        homes = {r.replica for r in reqs[:3]}
        assert len(homes) == 1
        assert fleet.stats["affinity_routes"] >= 2
        hits = sum(rep.engine._kv.stats["prefix_hits"]
                   for rep in fleet.replicas)
        assert hits >= 2        # the warm-cache payoff of pinning
        assert {r.replica for r in reqs} == {0, 1}   # others balanced


class TestPriorityScheduling:
    def test_priority_orders_dispatch(self, gpt):
        """Fleet-level dispatch respects SLO ordering: with one
        single-slot replica, an interactive request submitted LAST is
        admitted first."""
        fleet = ServingFleet(gpt, num_replicas=1, num_slots=1, chunk=4,
                             prefill_buckets=(8,), replica_queue_limit=0)
        ps = _prompts(4, (5, 5, 5))
        rb = fleet.submit(ps[0], 2, priority="batch")
        rs = fleet.submit(ps[1], 2, priority="standard")
        ri = fleet.submit(ps[2], 2, priority="interactive")
        fleet.run(threads=False, timeout=120)
        order = sorted((rb, rs, ri), key=lambda r: r.admit_ns)
        assert [r.req_id for r in order] == [ri.req_id, rs.req_id,
                                             rb.req_id]

    def test_aging_prevents_starvation(self, gpt):
        """A parked batch request eventually outranks fresh interactive
        traffic (eff rank drops one per aging_ms waited)."""
        import time as _time
        fleet = ServingFleet(gpt, num_replicas=1, num_slots=1, chunk=4,
                             prefill_buckets=(8,), replica_queue_limit=0,
                             aging_ms=1.0)
        ps = _prompts(5, (5, 5))
        rb = fleet.submit(ps[0], 2, priority="batch")
        _time.sleep(0.01)        # >= 2 aging periods: rank 2 -> 0
        ri = fleet.submit(ps[1], 2, priority="interactive")
        fleet.run(threads=False, timeout=120)
        assert rb.admit_ns < ri.admit_ns
        assert fleet.stats["aged"] >= 1

    def test_shed_terminal_callback_and_event(self, gpt):
        """Over-SLO best-effort traffic is shed with a terminal
        callback (reason 'shed') and a router_shed guardian event;
        higher classes are never shed."""
        fleet = ServingFleet(gpt, num_replicas=1, num_slots=1, chunk=2,
                             prefill_buckets=(8,), replica_queue_limit=1,
                             service_ms_prior=1e6)
        ps = _prompts(6, (5, 5, 5, 5))
        # budget 8 over chunk 2 keeps the slot busy across dispatch
        # gaps, so the projection sees a genuinely saturated replica
        std = [fleet.submit(p, 8, priority="standard") for p in ps[:3]]
        sheds = []
        rb = fleet.submit(ps[3], 2, priority="batch", slo_ttft_ms=1.0,
                          callback=lambda r, t, last:
                          sheds.append((r.req_id, t, last)))
        done = fleet.run(threads=False, timeout=120)
        assert rb.finish_reason == "shed"
        assert sheds == [(rb.req_id, None, True)]
        assert all(r.finish_reason == "budget" for r in std)
        assert fleet.stats["shed"] == 1
        evs = guardian.events("router_shed")
        assert evs and evs[-1]["req_id"] == rb.req_id
        assert evs[-1]["slo_ttft_ms"] == 1.0
        assert len(done) == 4    # shed requests are still returned

    def test_defer_policy_keeps_best_effort(self, gpt):
        """overload_policy='defer' parks over-SLO best-effort traffic
        instead of shedding; it completes once the backlog clears."""
        fleet = ServingFleet(gpt, num_replicas=1, num_slots=1, chunk=4,
                             prefill_buckets=(8,), replica_queue_limit=1,
                             service_ms_prior=50.0,
                             overload_policy="defer")
        ps = _prompts(7, (5, 5, 5))
        std = [fleet.submit(p, 2, priority="standard") for p in ps[:2]]
        rb = fleet.submit(ps[2], 2, priority="batch", slo_ttft_ms=0.001)
        fleet.run(threads=False, timeout=120)
        assert rb.finish_reason == "budget"        # never shed
        assert fleet.stats["shed"] == 0
        assert max(s.admit_ns for s in std) < rb.admit_ns


class TestReplicaLifecycle:
    @pytest.mark.chaos
    def test_replica_crash_requeues_bitwise(self, gpt):
        """THE chaos acceptance: kill a replica mid-decode; its queued
        + in-flight requests requeue to the survivor and ALL requests
        complete with bitwise-correct output (resume-by-recompute)."""
        prompts = _prompts(8, (5, 11, 8, 3, 7, 9))
        refs = [_gen(gpt, p, 8) for p in prompts]
        fleet = ServingFleet(gpt, num_replicas=2, num_slots=2, chunk=4,
                             prefill_buckets=(8, 16, 32))
        failpoints.set_failpoint("serving.replica_crash", "error*1")
        reqs = [fleet.submit(p, 8) for p in prompts]
        done = fleet.run(threads=False, timeout=120)
        assert fleet.stats["replica_deaths"] == 1
        assert fleet.stats["requeued"] >= 1
        dead = [rep for rep in fleet.replicas if rep.state == "dead"]
        assert len(dead) == 1 and "Failpoint" in dead[0].error
        for r, ref in zip(done, refs):
            np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                          ref)
        survivor = (set(range(2)) - {dead[0].idx}).pop()
        moved = [r for r in done if r.evictions > 0]
        assert moved and all(r.replica == survivor for r in moved)
        evs = guardian.events("router_replica_death")
        assert evs and evs[-1]["replica"] == dead[0].idx

    @pytest.mark.chaos
    def test_threaded_paged_crash_bitwise(self, gpt):
        """Same chaos through worker threads and the paged KV engine
        (pages freed on drain, prefix state rebuilt)."""
        prompts = _prompts(9, (5, 11, 8, 9))
        refs = [_gen(gpt, p, 6) for p in prompts]
        fleet = ServingFleet(gpt, num_replicas=2, num_slots=2, chunk=4,
                             kv_mode="paged", page_size=8,
                             prefill_buckets=(8, 16, 32),
                             max_seq_len=128)
        failpoints.set_failpoint("serving.replica_crash", "error*1")
        reqs = [fleet.submit(p, 6) for p in prompts]
        fleet.run(threads=True, timeout=120)
        assert fleet.stats["replica_deaths"] == 1
        for r, ref in zip(reqs, refs):
            np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                          ref)
        for rep in fleet.replicas:       # no leaked pages anywhere
            if rep.state == "up":
                assert rep.engine._kv.check()

    def test_remove_replica_drains_and_requeues(self, gpt, fleet2):
        fleet2.reset()
        prompts = _prompts(10, (5, 5, 5, 5))
        reqs = [fleet2.submit(p, 3) for p in prompts]
        fleet2._dispatch()               # park work on both replicas
        n = fleet2.remove_replica(1)
        assert n >= 1
        assert fleet2.stats["requeued"] == n
        done = fleet2.run(threads=False, timeout=120)
        assert all(r.finish_reason == "budget" for r in done)
        assert all(r.replica == 0 for r in done)
        with pytest.raises(RuntimeError, match="last routable"):
            fleet2.remove_replica(0)
        # restore the module fleet for later tests
        fleet2.replicas[1].state = "up"
        fleet2.replicas[1].retire.clear()
        fleet2.reset()

    def test_autoscale_recommendation(self, gpt):
        fleet = ServingFleet(gpt, num_replicas=2, num_slots=1, chunk=2,
                             prefill_buckets=(8,),
                             scale_up_queue_per_replica=2.0)
        # an idle multi-replica fleet recommends retiring a replica
        assert fleet.autoscale_recommendation() == -1
        for p in _prompts(11, (5,) * 12):
            fleet.submit(p, 8)
        fleet._dispatch()
        for rep in fleet.replicas:       # occupy every slot
            fleet._step_replica(rep)
        rec = fleet.autoscale_recommendation()
        assert rec == 1                  # deep backlog, full occupancy
        fleet.run(threads=False, timeout=120)
        assert fleet.autoscale_recommendation() == -1  # idle again
        assert guardian.events("router_scale")

    def test_export_import_pages_roundtrip(self):
        """The disaggregation seam: a slot's pages survive an
        export->import hop into another manager's pool bit-for-bit."""
        spec = [(2, 4), (2, 4)]
        a = kvcache.PagedKVManager(spec, 2, 32, 8, 9, "float32")
        b = kvcache.PagedKVManager(spec, 2, 32, 8, 9, "float32")
        prompt = np.arange(16, dtype=np.int32)
        plan = a.plan(prompt, 8, 8)
        a.bind(0, plan)
        rng = np.random.RandomState(0)
        pools = [tuple(buf.at[1:3].set(rng.randn(2, 8, 2, 4)
                                       .astype("float32"))
                       for buf in pools) for pools in a.device_pools()]
        a.set_pools(pools)
        payload = a.export_pages(0)
        assert payload["logical"] == sorted(a._slot_pages[0])
        n = b.import_pages(1, payload)
        assert n == len(payload["logical"])
        assert b.check()
        got = b.export_pages(1)
        for la, lb in zip(payload["layers"], got["layers"]):
            for xa, xb in zip(la, lb):
                np.testing.assert_array_equal(xa, xb)


class TestFleetObservability:
    def test_route_span_tiling_and_per_replica(self, gpt, fleet2):
        """Every routed request books route -> queue_wait -> prefill
        (-> decode) spans that tile submit -> finish, each carrying the
        replica label; report --per-replica groups them."""
        fleet2.reset()
        prompts = _prompts(12, (5, 11, 8, 3))
        reqs = [fleet2.submit(p, 6) for p in prompts]
        fleet2.run(threads=False, timeout=120)
        rows = tracing.request_summaries()
        assert len(rows) == len(reqs)
        for row in rows:
            assert {"route", "queue_wait", "prefill"} <= \
                set(row["phase_ms"])
            assert row["replica"] in (0, 1)
            assert row["span_sum_ms"] == pytest.approx(
                row["total_ms"], rel=0.01, abs=0.05)
        views = report.per_replica_views(rows)
        assert set(views) <= {"0", "1"}
        assert sum(v["requests"] for v in views.values()) == len(reqs)

    def test_report_per_replica_cli(self, gpt, fleet2, tmp_path,
                                    capsys):
        fleet2.reset()
        for p in _prompts(13, (5, 9, 7)):
            fleet2.submit(p, 4)
        fleet2.run(threads=False, timeout=120)
        trace = str(tmp_path / "t.trace.json")
        timeline.export_chrome_trace(trace, include_profiler=False,
                                     include_guardian=False,
                                     include_samples=False)
        rc = report.main(["report", "--trace", trace, "--requests",
                          "--per-replica", "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["per_replica"]
        assert sum(v["requests"] for v in out["per_replica"].values()) \
            == 3
        assert report.main(["report", "--trace", trace,
                            "--per-replica"]) == 2   # needs --requests

    def test_router_metrics_recorded(self, gpt, fleet2):
        fleet2.reset()
        for p in _prompts(14, (5, 9)):
            fleet2.submit(p, 3, priority="interactive")
        fleet2.run(threads=False, timeout=120)
        reg = obs.get_registry()
        assert reg.get("pt_router_requests_total").value(
            priority="interactive") == 2
        routed = reg.get("pt_router_routed_total")
        total = sum(s[1] for s in routed.series())
        assert total == 2
        assert reg.get("pt_router_queue_depth") is not None
        evs = guardian.events("router_stats")
        assert evs and evs[-1]["requests"] == 2

    def test_zero_new_host_sync_ab(self, gpt, monkeypatch):
        """The PR 5/9 A/B extended to the fleet: routing + route spans
        + router metrics add ZERO device transfers (serial mode, so the
        chunk schedule is deterministic across legs)."""
        lock = threading.Lock()
        counts = {"n": 0}
        real = jax.device_get

        def counting(x):
            with lock:
                counts["n"] += 1
            return real(x)

        def run_once(enabled):
            fleet = ServingFleet(gpt, num_replicas=2, num_slots=2,
                                 chunk=4, prefill_buckets=(8, 16))
            for p in _prompts(15, (5, 11, 8, 3)):
                fleet.submit(p, 5)
            counts["n"] = 0
            monkeypatch.setattr(jax, "device_get", counting)
            try:
                if enabled:
                    fleet.run(threads=False, timeout=120)
                else:
                    with obs.disabled():
                        fleet.run(threads=False, timeout=120)
            finally:
                monkeypatch.setattr(jax, "device_get", real)
            chunks = sum(r.engine.stats["chunks"]
                         for r in fleet.replicas)
            return counts["n"], chunks

        n_on, chunks_on = run_once(True)
        n_off, chunks_off = run_once(False)
        assert chunks_on == chunks_off
        assert n_on == n_off > 0
        assert len(tracing.spans()) > 0   # tracing DID run in the on leg


@pytest.mark.lint
class TestRouterLintSelfCheck:
    def test_failpoint_registered(self):
        import paddle_tpu.inference.router  # noqa: F401 — registers
        assert "serving.replica_crash" in failpoints.registered()

    def test_router_concurrency_and_sync_lints_clean(self):
        """The router's locked regions satisfy the concurrency pass and
        its one budgeted sync satisfies host-sync — with the committed
        baseline still EMPTY."""
        from paddle_tpu.analysis import runner
        findings = runner.run_passes(
            paths=["paddle_tpu/inference/router.py",
                   "paddle_tpu/inference/scheduler.py",
                   "paddle_tpu/inference/serving.py",
                   "paddle_tpu/inference/kvcache.py"],
            passes=["concurrency", "host-sync"])
        assert findings == []
        import os
        base = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "lint_baseline.json")
        with open(base, encoding="utf-8") as f:
            assert not json.load(f)["findings"]      # baseline EMPTY
