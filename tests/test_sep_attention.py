"""Sequence-parallel (sep axis) attention parity: ring + Ulysses over a
4-device mesh == dense single-device attention (reference pattern:
hybrid-parallel runs vs single-process golden, SURVEY.md §4)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.ops.ring_attention import (ring_flash_attention,
                                           ulysses_attention)
from paddle_tpu.nn.functional.attention import _xla_attention


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)
    except ImportError:
        from jax.experimental.shard_map import shard_map
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


def _mesh(n=4):
    return Mesh(np.asarray(jax.devices()[:n]), ("sep",))


def test_ring_attention_2d_mesh_dp_sep():
    """dp×sep mesh: the production layout — carry vma must track both
    axes (regression for the shard_map varying-manual-axes check)."""
    import math
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.ops.ring_attention import ring_flash_attention
    mesh2 = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2),
                 ("data", "sep"))
    rng = np.random.RandomState(0)
    B, S, H, D = 8, 16, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
    sh = NamedSharding(mesh2, P("data", "sep"))
    qd, kd, vd = (jax.device_put(t, sh) for t in (q, k, v))

    @jax.jit
    def run(q, k, v):
        return _shard_map(
            lambda a, b, c: ring_flash_attention(a, b, c, "sep",
                                                 causal=True),
            mesh2, (P("data", "sep"),) * 3, P("data", "sep"))(q, k, v)

    out = np.asarray(run(qd, kd, vd))
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(D)
    mask = np.tril(np.ones((S, S), bool))
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def _qkv(B=2, S=32, H=4, D=8, Hk=None, seed=0):
    rng = np.random.RandomState(seed)
    Hk = Hk or H
    q = rng.randn(B, S, H, D).astype("f4")
    k = rng.randn(B, S, Hk, D).astype("f4")
    v = rng.randn(B, S, Hk, D).astype("f4")
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    q, k, v = _qkv()
    mesh = _mesh(4)
    spec = P(None, "sep", None, None)
    fn = _shard_map(
        lambda a, b, c: ring_flash_attention(a, b, c, "sep", causal=causal),
        mesh, (spec, spec, spec), spec)
    out = fn(q, k, v)
    ref = _xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    q, k, v = _qkv()
    mesh = _mesh(4)
    spec = P(None, "sep", None, None)
    fn = _shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, "sep", causal=causal),
        mesh, (spec, spec, spec), spec)
    out = fn(q, k, v)
    ref = _xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_gqa():
    q, k, v = _qkv(H=4, Hk=2)
    mesh = _mesh(4)
    qs = P(None, "sep", None, None)
    fn = _shard_map(
        lambda a, b, c: ring_flash_attention(a, b, c, "sep", causal=True),
        mesh, (qs, qs, qs), qs)
    out = fn(q, k, v)
    ref = _xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_grads_match_dense():
    q, k, v = _qkv(B=1, S=16, H=2, D=4)
    mesh = _mesh(4)
    spec = P(None, "sep", None, None)
    ring = _shard_map(
        lambda a, b, c: ring_flash_attention(a, b, c, "sep", causal=True),
        mesh, (spec, spec, spec), spec)

    def loss_ring(a, b, c):
        return jnp.sum(ring(a, b, c) ** 2)

    def loss_ref(a, b, c):
        return jnp.sum(_xla_attention(a, b, c, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-4, atol=2e-5)


def test_sep_attention_tensor_api():
    """Tensor-level sep_utils wrapper inside a jitted shard_map region."""
    from paddle_tpu.distributed.fleet.utils.sep_utils import sep_attention
    from paddle_tpu.framework.core import Tensor
    q, k, v = _qkv(S=16)
    mesh = _mesh(4)
    spec = P(None, "sep", None, None)

    def body(a, b, c):
        out = sep_attention(Tensor(a), Tensor(b), Tensor(c), is_causal=True)
        return out._value

    fn = _shard_map(body, mesh, (spec, spec, spec), spec)
    out = jax.jit(fn)(q, k, v)
    ref = _xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
