"""Continuous-batching serving engine (inference/serving.py +
scheduler.py): greedy parity vs generate(), slot lifecycle, in-flight
admission, eos eviction, ragged-prompt bucket prefill, and the
attention_mask satellite on generate() itself.

The parity tests are the real check of the per-slot vector-pos KV math:
the engine's bucket prefill + chunked scan must reproduce, token for
token, the single-scan generate() path."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework import guardian
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.inference.scheduler import FCFSScheduler
from paddle_tpu.models import GPTForPretraining, gpt3_tiny

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    return GPTForPretraining(gpt3_tiny())


def _gen(gpt, prompt_np, n, **kw):
    """generate() reference for a single prompt / uniform batch."""
    if prompt_np.ndim == 1:
        prompt_np = prompt_np[None, :]
    ids, _ = gpt.generate(paddle.to_tensor(prompt_np), max_new_tokens=n,
                          **kw)
    return np.asarray(ids._value)


class TestGreedyParity:
    def test_uniform_batch_bitwise_matches_generate(self, gpt):
        """Acceptance: uniform-length, uniform-budget batch — engine
        output bitwise-identical to generate()."""
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 1024, (3, 8)).astype("int32")
        ref = _gen(gpt, ids, 6)
        eng = ServingEngine(gpt, num_slots=3, chunk=4,
                            prefill_buckets=(8, 16))
        reqs = [eng.submit(ids[r], 6) for r in range(3)]
        done = eng.run()
        assert [r.req_id for r in done] == [r.req_id for r in reqs]
        got = np.stack([np.asarray(r.tokens, np.int32) for r in done])
        np.testing.assert_array_equal(got, ref)

    def test_ragged_prompts_bucket_prefill_matches_single(self, gpt):
        """Ragged prompts pad to power-of-two buckets; the pad KV sits
        after the real tokens and must never leak into the output —
        every request matches its own B=1 generate() run bitwise."""
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 1024, (n,)).astype("int32")
                   for n in (5, 11, 8, 3)]
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8, 16))
        reqs = [eng.submit(p, 5) for p in prompts]
        eng.run()
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, 5)[0])


class TestSlotLifecycle:
    def test_staggered_budgets_reuse_slots(self, gpt):
        """4 requests through 2 slots with staggered max_new_tokens:
        early finishers must free their slot for the queue (the
        continuous-batching win) and every request still matches its
        solo generate() run."""
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 1024, (6,)).astype("int32")
                   for _ in range(4)]
        budgets = [3, 9, 5, 7]
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8,))
        reqs = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
        done = eng.run()
        assert len(done) == 4 and eng.stats["prefills"] == 4
        assert eng.stats["max_concurrent"] == 2
        assert not eng.scheduler.has_work
        for p, b, r in zip(prompts, budgets, reqs):
            assert len(r.tokens) == b and r.finish_reason == "budget"
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, b)[0])

    def test_admission_mid_flight(self, gpt):
        """A request submitted while another is decoding must be
        admitted at the next chunk boundary — not after the first
        request drains (the static-batch failure mode)."""
        rng = np.random.RandomState(4)
        p1 = rng.randint(0, 1024, (6,)).astype("int32")
        p2 = rng.randint(0, 1024, (4,)).astype("int32")
        eng = ServingEngine(gpt, num_slots=2, chunk=2,
                            prefill_buckets=(8,))
        a = eng.submit(p1, 8)
        eng.step()                       # a is mid-flight (8 > chunk=2)
        assert not a.done
        b = eng.submit(p2, 4)
        eng.step()                       # b admitted beside a
        assert eng.stats["max_concurrent"] == 2
        while eng.scheduler.has_work:
            eng.step()
        np.testing.assert_array_equal(np.asarray(a.tokens, np.int32),
                                      _gen(gpt, p1, 8)[0])
        np.testing.assert_array_equal(np.asarray(b.tokens, np.int32),
                                      _gen(gpt, p2, 4)[0])

    def test_eos_evicts_and_frees_slot(self, gpt):
        """A slot hitting eos stops early (finish_reason "eos", token
        stream ends at the eos) instead of burning its budget."""
        rng = np.random.RandomState(5)
        p = rng.randint(0, 1024, (7,)).astype("int32")
        ref = _gen(gpt, p, 9)[0]
        eos = int(ref[2])                # a token greedy decode emits
        first = int(np.argmax(ref == eos))
        eng = ServingEngine(gpt, num_slots=1, chunk=8,
                            prefill_buckets=(8,), eos_token_id=eos)
        r = eng.submit(p, 9)
        eng.run()
        assert r.finish_reason == "eos"
        assert r.tokens[-1] == eos and len(r.tokens) == first + 1
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      ref[:first + 1])

    def test_streaming_callback_order_and_is_last(self, gpt):
        rng = np.random.RandomState(6)
        p = rng.randint(0, 1024, (5,)).astype("int32")
        seen = []
        eng = ServingEngine(gpt, num_slots=1, chunk=3,
                            prefill_buckets=(8,))
        r = eng.submit(p, 5, callback=lambda rq, t, last:
                       seen.append((rq.req_id, t, last)))
        eng.run()
        assert [t for _, t, _ in seen] == r.tokens
        assert [last for _, _, last in seen] == \
            [False] * 4 + [True]
        assert r.ttft_ms is not None and r.ttft_ms >= 0

    def test_submit_validation(self, gpt):
        eng = ServingEngine(gpt, num_slots=1, chunk=2,
                            prefill_buckets=(8, 16))
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError, match="largest"):
            eng.submit(np.zeros((17,), np.int32), 4)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.submit(np.zeros((8,), np.int32), 1000)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.zeros((4,), np.int32), 0)
        with pytest.raises(ValueError, match="bucket"):
            # bucket == max_seq_len leaves no room to generate
            ServingEngine(gpt, num_slots=1, max_seq_len=16,
                          prefill_buckets=(16,))

    def test_reset_reuses_compiled_programs(self, gpt):
        rng = np.random.RandomState(7)
        p = rng.randint(0, 1024, (6,)).astype("int32")
        eng = ServingEngine(gpt, num_slots=1, chunk=4,
                            prefill_buckets=(8,))
        r1 = eng.submit(p, 4)
        eng.run()
        jits = (eng._decode_jit, eng._prefill_jit)
        eng.reset()
        assert eng.stats["requests"] == 0
        assert (eng._decode_jit, eng._prefill_jit) == jits
        r2 = eng.submit(p, 4)
        eng.run()
        assert r2.tokens == r1.tokens

    def test_refresh_weights_keeps_dtype_override(self):
        """A dtype override must survive refresh_weights() even when the
        model's own params are mixed-dtype with the override dtype
        already dominant (an uncast fp32 norm would silently retrace the
        decode program with mixed dtypes)."""
        paddle.seed(3)
        net = GPTForPretraining(gpt3_tiny())
        params = [p for _, p in net.named_parameters()]
        floats = [p for p in params
                  if jnp.issubdtype(p._value.dtype, jnp.floating)]
        keep_fp32 = min(floats, key=lambda p: p._value.size)
        for p in floats:                 # mostly-bf16 model, one fp32 norm
            if p is not keep_fp32:
                p._value = p._value.astype(jnp.bfloat16)
        eng = ServingEngine(net, num_slots=1, chunk=2, dtype="bfloat16",
                            prefill_buckets=(8,))

        def float_dtypes(pvals):
            return {str(v.dtype) for v in pvals
                    if jnp.issubdtype(v.dtype, jnp.floating)}
        assert float_dtypes(eng._pvals) == {"bfloat16"}
        params[0]._value = params[0]._value + 0   # "train step": new array
        eng.refresh_weights()
        assert float_dtypes(eng._pvals) == {"bfloat16"}


class TestGuardianEvents:
    def test_admit_finish_stats_emitted(self, gpt):
        guardian.clear_events()
        rng = np.random.RandomState(8)
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(8,))
        for _ in range(3):
            eng.submit(rng.randint(0, 1024, (6,)).astype("int32"), 4)
        eng.run()
        admits = guardian.events("serving_admit")
        fins = guardian.events("serving_finish")
        stats = guardian.events("serving_stats")
        assert len(admits) == 3 and len(fins) == 3 and len(stats) == 1
        assert {a["slot"] for a in admits} <= {0, 1}
        assert all(f["reason"] == "budget" and f["tokens"] == 4
                   for f in fins)
        s = stats[-1]
        assert s["requests"] == 3 and s["decoded_tokens"] == 12
        assert s["tokens_per_sec"] > 0 and s["mean_ttft_ms"] > 0


class TestScheduler:
    def test_fcfs_order_and_interleave_knob(self):
        s = FCFSScheduler(4, max_prefills_per_gap=2)
        reqs = [s.submit(np.zeros(2, np.int32), 4) for _ in range(5)]
        first = s.admissions()
        assert [r.req_id for r, _ in first] == [reqs[0].req_id,
                                                reqs[1].req_id]
        second = s.admissions()          # knob caps at 2 per gap
        assert len(second) == 2 and s.queue_depth == 1
        assert s.admissions() == []      # no free slots left
        s.release(first[0][1])
        third = s.admissions()
        assert [r.req_id for r, _ in third] == [reqs[4].req_id]
        assert third[0][1] == first[0][1]     # freed slot reused

    def test_validation(self):
        with pytest.raises(ValueError):
            FCFSScheduler(0)
        with pytest.raises(ValueError):
            FCFSScheduler(2, max_prefills_per_gap=0)


class TestAttentionMask:
    """Satellite: generate() folds an attention_mask into the additive
    prefill/decode mask so left-padded ragged prompts stop silently
    attending pad tokens."""

    def test_pad_content_is_irrelevant_under_mask(self, gpt):
        """Two left-padded batches that differ ONLY in the pad cells
        must decode identically when the mask excludes those cells —
        the defining property of not attending pads."""
        rng = np.random.RandomState(9)
        real = rng.randint(1, 1024, (2, 5)).astype("int32")
        mask = np.ones((2, 9), np.int32)
        mask[:, :4] = 0
        a = np.concatenate([np.zeros((2, 4), np.int32), real], axis=1)
        b = np.concatenate(
            [rng.randint(1, 1024, (2, 4)).astype("int32"), real], axis=1)
        out_a = _gen(gpt, a, 6, attention_mask=mask)
        out_b = _gen(gpt, b, 6, attention_mask=mask)
        np.testing.assert_array_equal(out_a, out_b)
        # and the mask actually changes the computation vs attending
        # pads (token-level greedy picks can coincide on a tiny random
        # model; the selected-token log-probs cannot)
        _, sc_masked = gpt.generate(paddle.to_tensor(b),
                                    max_new_tokens=6,
                                    attention_mask=mask)
        _, sc_plain = gpt.generate(paddle.to_tensor(b),
                                   max_new_tokens=6)
        assert not np.array_equal(np.asarray(sc_masked._value),
                                  np.asarray(sc_plain._value))

    def test_mask_matches_tensor_and_array_inputs(self, gpt):
        rng = np.random.RandomState(10)
        ids = rng.randint(1, 1024, (2, 6)).astype("int32")
        mask = np.ones((2, 6), np.int32)
        mask[0, :2] = 0
        out_np = _gen(gpt, ids, 4, attention_mask=mask)
        out_t = _gen(gpt, ids, 4,
                     attention_mask=paddle.to_tensor(mask))
        np.testing.assert_array_equal(out_np, out_t)

    def test_all_ones_mask_is_bitwise_noop(self, gpt):
        rng = np.random.RandomState(11)
        ids = rng.randint(0, 1024, (2, 6)).astype("int32")
        np.testing.assert_array_equal(
            _gen(gpt, ids, 5),
            _gen(gpt, ids, 5, attention_mask=np.ones((2, 6), np.int32)))

    def test_beam_search_accepts_mask(self, gpt):
        rng = np.random.RandomState(12)
        real = rng.randint(1, 1024, (1, 4)).astype("int32")
        a = np.concatenate([np.zeros((1, 3), np.int32), real], axis=1)
        b = np.concatenate(
            [rng.randint(1, 1024, (1, 3)).astype("int32"), real], axis=1)
        mask = np.ones((1, 7), np.int32)
        mask[:, :3] = 0
        kw = dict(decode_strategy="beam_search", num_beams=2,
                  attention_mask=mask)
        np.testing.assert_array_equal(_gen(gpt, a, 4, **kw),
                                      _gen(gpt, b, 4, **kw))

    def test_bad_mask_shape_raises(self, gpt):
        ids = np.zeros((2, 6), np.int32)
        with pytest.raises(ValueError, match="attention_mask"):
            _gen(gpt, ids, 4, attention_mask=np.ones((2, 5), np.int32))
