"""Global-norm grad clip correctness across sharded meshes (VERDICT r1
weak #9): clip under ZeRO-3 / TP must equal the single-device clip on
the same data — reference pattern: hybrid_parallel clip tests in
test/collective/fleet/hybrid_parallel_mp_clip.py."""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.clip import ClipGradByGlobalNorm
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


CLIP = 0.05  # far below the natural grad norm so clipping always bites


def _data(steps=3, B=8, S=16, V=512, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, V, (B, S)).astype("i8"),
             rng.randint(0, V, (B, S)).astype("i8")) for _ in range(steps)]


def _train(net, opt, data):
    model = paddle.Model(net)
    model.prepare(opt, nn.CrossEntropyLoss())
    return [model.train_batch([x], [y[..., None]])[0] for x, y in data]


def test_zero3_clip_matches_single_device():
    assert jax.device_count() == 8
    cfg = llama_tiny()
    data = _data()

    paddle.seed(11)
    golden = LlamaForCausalLM(cfg)
    gopt = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=golden.parameters(),
        grad_clip=ClipGradByGlobalNorm(CLIP))
    golden_losses = _train(golden, gopt, data)
    assert all(np.isfinite(l) for l in golden_losses)

    paddle.seed(11)
    net = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=net.parameters(),
        grad_clip=ClipGradByGlobalNorm(CLIP))
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    wrapped, opt, _ = group_sharded_parallel(net, opt, level="p_g_os")
    losses = _train(wrapped, opt, data)

    # lr=1e-2 with clip active: any clip-norm error (e.g. a shard-local
    # norm) would compound over steps and blow the tolerance
    np.testing.assert_allclose(losses, golden_losses, rtol=3e-4, atol=3e-5)
    big = [p for p in net.parameters() if len(p.shape) >= 2 and
           int(np.prod(p.shape)) >= 64 * 64]
    assert any(not p._value.sharding.is_fully_replicated for p in big)


def test_tp2_clip_matches_single_device():
    from test_tensor_parallel import MPBlock, PlainBlock, _sync_weights
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    golden = PlainBlock()
    mp = MPBlock()
    _sync_weights(golden, mp)
    dmp = fleet.distributed_model(mp)

    ids = np.random.RandomState(0).randint(0, 32, (8, 6)).astype("i8")
    tgt = np.random.RandomState(1).rand(8, 6, 16).astype("f4")

    opt = fleet.distributed_optimizer(paddle.optimizer.SGD(
        learning_rate=0.5, parameters=mp.parameters(),
        grad_clip=ClipGradByGlobalNorm(CLIP)))
    model = paddle.Model(dmp)
    model.prepare(opt, nn.MSELoss())

    gopt = paddle.optimizer.SGD(learning_rate=0.5,
                                parameters=golden.parameters(),
                                grad_clip=ClipGradByGlobalNorm(CLIP))
    gmodel = paddle.Model(golden)
    gmodel.prepare(gopt, nn.MSELoss())

    for _ in range(3):
        res = model.train_batch([ids], [tgt])
        gres = gmodel.train_batch([ids], [tgt])
        np.testing.assert_allclose(res[0], gres[0], rtol=2e-4, atol=1e-5)

    # sharded TP weight equals the golden after clipped steps — a wrong
    # global norm (per-shard instead of logical) would scale the update
    assert not mp.up.weight._value.sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(mp.up.weight._value),
                               golden.up.weight.numpy(), rtol=2e-4,
                               atol=1e-5)
