"""Sharding & numerics lint suite tests (ISSUE 18): mesh-axes,
dtype-flow and spec-drift, each exercised both ways — seeded-violation
fixtures the pass MUST flag, and known-good idioms (including the
contract allowlists) it must NOT flag.  The self-lint test runs the
three passes over the real tree and must come back empty against the
EMPTY committed baseline: the tree itself is the permanent TN fixture.
"""
import textwrap

import pytest

from paddle_tpu.analysis import base as _base
from paddle_tpu.analysis.allowlist import COMPILE_SURFACES, MESH_AXES
from paddle_tpu.analysis.runner import make_context, run_passes

pytestmark = pytest.mark.lint

SHARDING_PASSES = ["mesh-axes", "dtype-flow", "spec-drift"]


def _lint(tmp_path, code, passes, name="fixture.py"):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    return run_passes(paths=[str(tmp_path)], passes=passes)


def _codes(findings):
    return [f.code for f in findings]


class TestMeshAxes:
    def test_flags_undeclared_and_duplicate_axis(self, tmp_path):
        found = _lint(tmp_path, """
            from jax.sharding import PartitionSpec as P

            SPEC_TYPO = P("dta", None)          # undeclared (typo)
            SPEC_DUP = P("data", "data")        # duplicate
            """, passes=["mesh-axes"])
        codes = _codes(found)
        assert "undeclared-axis" in codes
        assert "duplicate-axis" in codes
        assert any(f.detail == "P:dta" for f in found)

    def test_conditional_spec_is_not_a_duplicate(self, tmp_path):
        # the gpt_hybrid idiom: the IfExp *test* also contains the
        # axis literal — value positions alone decide duplication
        found = _lint(tmp_path, """
            from jax.sharding import PartitionSpec as P

            def spec(has):
                return P("data" if "data" in has else None, None)
            """, passes=["mesh-axes"])
        assert found == []

    def test_flags_shard_map_arity_mismatch(self, tmp_path):
        found = _lint(tmp_path, """
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def kernel(x):
                return x

            def build(mesh):
                return shard_map(kernel, mesh,
                                 in_specs=(P("data"), P(None)),
                                 out_specs=P("data"))
            """, passes=["mesh-axes"])
        assert "spec-arity-mismatch" in _codes(found)

    def test_matching_arity_is_clean(self, tmp_path):
        found = _lint(tmp_path, """
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def kernel(x, y):
                return x + y

            def build(mesh):
                return shard_map(kernel, mesh,
                                 in_specs=(P("data"), P(None)),
                                 out_specs=P("data"))
            """, passes=["mesh-axes"])
        assert found == []

    def test_flags_unbound_collective_axis_name(self, tmp_path):
        found = _lint(tmp_path, """
            from jax import lax

            def reduce(x):
                return lax.psum(x, "data")   # nothing binds 'data'
            """, passes=["mesh-axes"])
        assert "unbound-axis-name" in _codes(found)

    def test_shard_map_binding_clears_collective(self, tmp_path):
        found = _lint(tmp_path, """
            from jax import lax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def reduce(x):
                return lax.psum(x, "data")

            def build(mesh):
                return shard_map(reduce, mesh, in_specs=(P("data"),),
                                 out_specs=P(None))
            """, passes=["mesh-axes"])
        assert found == []

    def test_pragma_suppresses(self, tmp_path):
        found = _lint(tmp_path, """
            from jax.sharding import PartitionSpec as P

            SPEC = P("dta", None)  # lint: allow(undeclared-axis)
            """, passes=["mesh-axes"])
        assert found == []


class TestDtypeFlow:
    def test_flags_fp32_upcast_on_jit_surface(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(x):
                return x.astype(jnp.float32)
            """, passes=["dtype-flow"])
        assert "fp32-upcast" in _codes(found)

    def test_contract_cast_is_exempt(self, tmp_path):
        # quantize_kv in a module matching the monitored relpath is in
        # FP32_CONTRACT_CASTS: the declared-accumulator exemption
        found = _lint(tmp_path, """
            import jax.numpy as jnp

            def quantize_kv(x):
                xf = x.astype(jnp.float32)
                amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
                scale = jnp.maximum(amax, 1e-30) / 127.0
                q = jnp.clip(jnp.round(xf / scale[..., None, None]),
                             -127.0, 127.0).astype(jnp.int8)
                return q, scale

            def dequantize_kv(q, scale, dtype):
                return (q.astype(jnp.float32)
                        * scale[..., None, None]).astype(dtype)
            """, passes=["dtype-flow"],
            name="paddle_tpu/inference/kvcache.py")
        assert found == []

    def test_flags_untyped_alloc(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(n):
                return jnp.zeros((n, 4))
            """, passes=["dtype-flow"])
        assert "untyped-alloc" in _codes(found)

    def test_explicit_dtype_alloc_is_clean(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(n):
                return jnp.zeros((n, 4), jnp.bfloat16)
            """, passes=["dtype-flow"])
        assert found == []

    def test_flags_unpaired_kv_quantize(self, tmp_path):
        found = _lint(tmp_path, """
            def write_cache(cache, x):
                q, scale = quantize_kv(x)   # dequantize_kv: nowhere
                return cache.store(q, scale)
            """, passes=["dtype-flow"])
        assert any(f.code == "unpaired-quantize" and
                   f.detail == "quantize_kv-without-dequantize_kv"
                   for f in found)

    def test_balanced_kv_pair_is_clean(self, tmp_path):
        found = _lint(tmp_path, """
            def roundtrip(cache, x, dtype):
                q, scale = quantize_kv(x)
                return dequantize_kv(q, scale, dtype)
            """, passes=["dtype-flow"])
        assert found == []

    def test_flags_unscaled_narrow_cast(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp

            def narrow(x):
                return jnp.round(x).astype(jnp.int8)
            """, passes=["dtype-flow"])
        assert "unscaled-narrow-cast" in _codes(found)

    def test_scaled_narrow_cast_is_clean(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp

            def narrow(x):
                amax = jnp.max(jnp.abs(x))
                scale = jnp.maximum(amax, 1e-30) / 127.0
                return jnp.round(x / scale).astype(jnp.int8), scale
            """, passes=["dtype-flow"])
        assert found == []

    def test_flags_equarx_narrow_without_dequant(self, tmp_path):
        found = _lint(tmp_path, """
            def reduce(x, scale):
                q = _to_narrow(x / scale, "int8")
                return all_to_all_wire(q)   # never widened back
            """, passes=["dtype-flow"])
        assert any(f.code == "unpaired-quantize" and
                   f.detail == "narrow-without-dequant" for f in found)

    def test_equarx_with_fp32_dequant_is_clean(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp

            def reduce(x, scale):
                q = _to_narrow(x / scale, "int8")
                return q.astype(jnp.float32) * scale
            """, passes=["dtype-flow"])
        assert found == []


class TestSpecDrift:
    def test_flags_undeclared_mesh_construction_axis(self, tmp_path):
        found = _lint(tmp_path, """
            from jax.sharding import Mesh

            def build(devs):
                return Mesh(devs, ("data", "oops"))
            """, passes=["spec-drift"])
        assert any(f.code == "mesh-axis-undeclared" and
                   f.detail == "oops" for f in found)

    def test_declared_mesh_construction_is_clean(self, tmp_path):
        found = _lint(tmp_path, """
            from jax.sharding import Mesh

            def build(devs):
                return Mesh(devs, ("data", "model"))
            """, passes=["spec-drift"])
        assert found == []

    def test_flags_stale_doc_ref(self, tmp_path):
        (tmp_path / "DISTRIBUTED.md").write_text(
            "see `paddle_tpu/no_such_module.py` for details\n")
        found = run_passes(paths=[str(tmp_path)], passes=["spec-drift"])
        assert any(f.code == "stale-doc-ref" and
                   f.detail == "paddle_tpu/no_such_module.py"
                   for f in found)

    def test_live_doc_ref_is_clean(self, tmp_path):
        (tmp_path / "paddle_tpu").mkdir()
        (tmp_path / "paddle_tpu" / "real.py").write_text("X = 1\n")
        (tmp_path / "DISTRIBUTED.md").write_text(
            "see `paddle_tpu/real.py` for details\n")
        found = run_passes(paths=[str(tmp_path)], passes=["spec-drift"])
        assert found == []

    def test_flags_drifted_grad_comm_doc_row(self, tmp_path):
        # the ISSUE-named fixture: a documented config key the real
        # GradCommConfig does not take, plus an undocumented parameter
        (tmp_path / "grad_comm.py").write_text(textwrap.dedent("""
            _QUANT_MODES = (None, "bf16", "int8")

            class GradCommConfig:
                def __init__(self, enabled, bucket_mb, quantize):
                    self.enabled = enabled
        """))
        (tmp_path / "DISTRIBUTED.md").write_text(textwrap.dedent("""
            ## Communication-efficient gradient reduction

            ```python
            grad_comm_configs = {
                "bucket_bm": 25,
                "quantize": "int8",
            }
            ```

            Wire modes: `"bf16"`, `"int8"`, `"fp8"`.
        """))
        found = run_passes(paths=[str(tmp_path)], passes=["spec-drift"])
        details = {(f.code, f.detail) for f in found}
        assert ("grad-comm-drift", "bucket_bm") in details   # typo'd key
        assert ("grad-comm-drift", "bucket_mb") in details   # missing row
        assert ("wire-mode-drift", "fp8") in details         # not accepted

    def test_matching_grad_comm_doc_is_clean(self, tmp_path):
        (tmp_path / "grad_comm.py").write_text(textwrap.dedent("""
            _QUANT_MODES = (None, "bf16", "int8")

            class GradCommConfig:
                def __init__(self, enabled, bucket_mb, quantize):
                    self.enabled = enabled
        """))
        (tmp_path / "DISTRIBUTED.md").write_text(textwrap.dedent("""
            ## Communication-efficient gradient reduction

            ```python
            grad_comm_configs = {
                "bucket_mb": 25,
                "quantize": "int8",
            }
            ```

            Wire modes: `"bf16"`, `"int8"`.
        """))
        found = run_passes(paths=[str(tmp_path)], passes=["spec-drift"])
        assert found == []

    def test_default_tree_flags_unused_axes_and_surface_drift(
            self, tmp_path):
        # fabricate a minimal default tree: only 'data' is used and no
        # wrap literal carries the declared surfaces — the aggregate
        # directions that only make sense on a full sweep
        pkg = tmp_path / "paddle_tpu"
        pkg.mkdir()
        (pkg / "mod.py").write_text(textwrap.dedent("""
            from jax.sharding import PartitionSpec as P
            from paddle_tpu.observability import compilestats

            SPEC = P("data")
            STEP_SURFACE = "fixture.step"

            def step(x):
                return compilestats.wrap("fixture.other", lambda: x)()
        """))
        (tmp_path / "tests").mkdir()
        (tmp_path / "docs").mkdir()
        ctx = make_context(root=str(tmp_path))
        assert ctx.default_tree
        found = run_passes(ctx=ctx, passes=["spec-drift"])
        details = {(f.code, f.detail) for f in found}
        for ax in MESH_AXES:
            if ax != "data":
                assert ("mesh-axis-unused", ax) in details
        assert ("mesh-axis-unused", "data") not in details
        # wrapped-but-undeclared and declared-but-unwrapped directions
        assert ("surface-drift", "fixture.other") in details
        assert ("surface-drift", "fixture.step") in details
        for label in COMPILE_SURFACES:
            assert ("surface-drift", label) in details

    def test_scoped_run_skips_aggregate_directions(self, tmp_path):
        # a partial run must not report absence-of-usage: vocabulary
        # completeness is only meaningful over the whole tree
        found = _lint(tmp_path, """
            from jax.sharding import PartitionSpec as P

            SPEC = P("data")
            """, passes=["spec-drift"])
        assert found == []


class TestSweepInfrastructure:
    def test_timings_and_module_cache(self, tmp_path):
        (tmp_path / "fixture.py").write_text("X = 1\n")
        timings = {}
        run_passes(paths=[str(tmp_path)], passes=SHARDING_PASSES,
                   timings=timings)
        assert set(timings) == set(SHARDING_PASSES) | {"total"}
        assert all(t >= 0 for t in timings.values())
        # second run over the unchanged tree reuses the parsed module
        key = (str(tmp_path / "fixture.py"), "fixture.py")
        cached = _base._MODULE_CACHE.get(key)
        assert cached is not None
        _, info = cached
        run_passes(paths=[str(tmp_path)], passes=["mesh-axes"])
        assert _base._MODULE_CACHE[key][1] is info

    def test_self_lint_sharding_passes_clean(self):
        # the committed baseline is EMPTY: the real tree must satisfy
        # the three new passes outright (declared contracts included)
        found = run_passes(passes=SHARDING_PASSES)
        assert _codes(found) == []
