"""Shared-memory DataLoader transport (reference pattern:
test/legacy_test/test_multiprocess_dataloader_* with
use_shared_memory=True)."""
import glob
import os

import numpy as np
import pytest

from paddle_tpu.framework import native
from paddle_tpu.io import shm

pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native lib unavailable")


def _leftover_segments():
    return glob.glob("/dev/shm/pt_batch_*")


def test_write_read_roundtrip():
    batch = [(np.arange(5000, dtype="f4").reshape(100, 50),
              np.asarray([3], dtype="i8")),
             {"x": np.ones((64, 64), "f4"), "label": 7, "name": "abc"}]
    meta = shm.write_batch(batch)
    assert meta is not None
    out = shm.read_batch(meta)
    np.testing.assert_array_equal(out[0][0], batch[0][0])
    np.testing.assert_array_equal(out[0][1], batch[0][1])
    np.testing.assert_array_equal(out[1]["x"], batch[1]["x"])
    assert out[1]["label"] == 7 and out[1]["name"] == "abc"
    # read_batch unlinks: segment gone
    assert meta["shm"] not in [os.path.basename(p)
                               for p in _leftover_segments()]


def test_small_batches_fall_back_to_pipe():
    tiny = [np.ones(4, "f4")]
    assert shm.write_batch(tiny, min_bytes=1 << 14) is None


def test_dataloader_multiprocess_shm_parity():
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __init__(self):
            self.rng = np.random.RandomState(0)
            self.data = self.rng.rand(64, 3, 32, 32).astype("f4")

        def __getitem__(self, i):
            return self.data[i], np.int64(i)

        def __len__(self):
            return 64

    ds = DS()
    ref = list(DataLoader(ds, batch_size=16, num_workers=0,
                          return_list=True))
    got = list(DataLoader(ds, batch_size=16, num_workers=2,
                          use_shared_memory=True, return_list=True))
    assert len(got) == len(ref)
    for (rx, ry), (gx, gy) in zip(ref, got):
        np.testing.assert_allclose(np.asarray(gx._value),
                                   np.asarray(rx._value))
        np.testing.assert_array_equal(np.asarray(gy._value),
                                      np.asarray(ry._value))
    assert not _leftover_segments()


def test_early_break_cleans_segments():
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return np.full((3, 64, 64), i, "f4")

        def __len__(self):
            return 48

    loader = DataLoader(DS(), batch_size=4, num_workers=2,
                        use_shared_memory=True, return_list=True)
    for i, batch in enumerate(loader):
        if i == 1:
            break  # abandon mid-epoch with batches still in flight
    # shutdown ran via the generator finally; no leaked /dev/shm entries
    import time
    time.sleep(0.3)
    assert not _leftover_segments()
