"""paddle.signal + incubate fused functionals (reference pattern:
test/legacy_test/test_stft_op.py, test_fused_rotary_position_embedding
.py — torch/numpy goldens)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.core import Tensor


class TestSignal:
    def test_stft_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(0).randn(2, 4000).astype("f4")
        win = np.hanning(400).astype("f4")
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=512,
                                  hop_length=160, win_length=400,
                                  window=paddle.to_tensor(win))
        ref = torch.stft(torch.tensor(x), n_fft=512, hop_length=160,
                         win_length=400, window=torch.tensor(win),
                         return_complex=True, center=True,
                         pad_mode="reflect")
        np.testing.assert_allclose(np.asarray(spec._value), ref.numpy(),
                                   atol=1e-4, rtol=1e-4)

    def test_istft_reconstructs(self):
        x = np.random.RandomState(1).randn(3000).astype("f4")
        win = np.hanning(512).astype("f4")
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=512,
                                  hop_length=128,
                                  window=paddle.to_tensor(win))
        rec = paddle.signal.istft(spec, n_fft=512, hop_length=128,
                                  window=paddle.to_tensor(win), length=3000)
        r = np.asarray(rec._value)
        assert r.shape == (3000,)
        # the frame grid covers the first 2944 samples; the rest is the
        # documented zero-pad (torch.istft length semantics)
        np.testing.assert_allclose(r[:2944], x[:2944], atol=1e-5)
        np.testing.assert_allclose(r[2944:], 0.0, atol=1e-7)

    def test_frame_overlap_add_roundtrip(self):
        x = np.arange(64, dtype="f4")
        f = paddle.signal.frame(paddle.to_tensor(x), 16, 16)  # no overlap
        assert tuple(f.shape) == (4, 16)
        back = paddle.signal.overlap_add(f, 16)
        np.testing.assert_allclose(np.asarray(back._value), x)


class TestIncubateFused:
    def test_fused_rms_norm_matches_manual(self):
        from paddle_tpu.incubate.nn.functional import fused_rms_norm
        rng = np.random.RandomState(0)
        x = rng.randn(4, 64).astype("f4")
        g = rng.rand(64).astype("f4")
        out = fused_rms_norm(paddle.to_tensor(x), paddle.to_tensor(g))
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * g
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5,
                                   atol=1e-5)

    def test_fused_rms_norm_residual(self):
        from paddle_tpu.incubate.nn.functional import fused_rms_norm
        rng = np.random.RandomState(1)
        x = rng.randn(4, 64).astype("f4")
        r = rng.randn(4, 64).astype("f4")
        g = np.ones(64, "f4")
        out, res = fused_rms_norm(paddle.to_tensor(x), paddle.to_tensor(g),
                                  residual=paddle.to_tensor(r))
        np.testing.assert_allclose(np.asarray(res._value), x + r, rtol=1e-6)
        pre = x + r
        ref = pre / np.sqrt((pre ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5,
                                   atol=1e-5)

    def test_fused_layer_norm_matches_nn(self):
        from paddle_tpu.incubate.nn.functional import fused_layer_norm
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(2)
        x = rng.randn(6, 32).astype("f4")
        g = rng.rand(32).astype("f4")
        b = rng.randn(32).astype("f4")
        out = fused_layer_norm(paddle.to_tensor(x), paddle.to_tensor(g),
                               paddle.to_tensor(b))
        ref = F.layer_norm(paddle.to_tensor(x), 32,
                           weight=paddle.to_tensor(g),
                           bias=paddle.to_tensor(b))
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value), rtol=1e-4,
                                   atol=1e-5)

    def test_rope_matches_llama_interleaved(self):
        from paddle_tpu.incubate.nn.functional import (
            fused_rotary_position_embedding)
        from paddle_tpu.models.llama import _rope
        rng = np.random.RandomState(3)
        q = rng.randn(2, 16, 4, 32).astype("f4")
        out_q, out_k, out_v = fused_rotary_position_embedding(
            paddle.to_tensor(q), use_neox_rotary_style=False)
        ref = _rope(jnp.asarray(q), 10000.0)
        np.testing.assert_allclose(np.asarray(out_q._value),
                                   np.asarray(ref), rtol=1e-5, atol=1e-5)
        assert out_k is None and out_v is None

    def test_rope_neox_rotates_halves(self):
        from paddle_tpu.incubate.nn.functional import (
            fused_rotary_position_embedding)
        q = np.zeros((1, 4, 1, 8), "f4")
        q[..., 0] = 1.0  # unit vector on the first half
        out_q, _, _ = fused_rotary_position_embedding(
            paddle.to_tensor(q), use_neox_rotary_style=True)
        o = np.asarray(out_q._value)
        # position 0: rotation is identity
        np.testing.assert_allclose(o[0, 0, 0], q[0, 0, 0], atol=1e-6)
        # later positions rotate energy into the second half
        assert abs(o[0, 3, 0, 4]) > 0

    def test_swiglu(self):
        from paddle_tpu.incubate.nn.functional import swiglu
        rng = np.random.RandomState(4)
        x = rng.randn(3, 8).astype("f4")
        out = swiglu(paddle.to_tensor(x))
        a, b = x[:, :4], x[:, 4:]
        ref = a / (1 + np.exp(-a)) * b
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5)

    def test_fused_dropout_add_eval(self):
        from paddle_tpu.incubate.nn.functional import fused_dropout_add
        x = np.ones((2, 4), "f4")
        y = np.full((2, 4), 2.0, "f4")
        out = fused_dropout_add(paddle.to_tensor(x), paddle.to_tensor(y),
                                p=0.5, training=False)
        np.testing.assert_allclose(np.asarray(out._value), x + y)


class TestAudio:
    def test_mel_scale_roundtrip(self):
        from paddle_tpu.audio import functional as AF
        freqs = np.asarray([0.0, 440.0, 1000.0, 4000.0, 8000.0])
        back = AF.mel_to_hz(AF.hz_to_mel(freqs))
        np.testing.assert_allclose(back, freqs, rtol=1e-6)
        back_htk = AF.mel_to_hz(AF.hz_to_mel(freqs, htk=True), htk=True)
        np.testing.assert_allclose(back_htk, freqs, rtol=1e-6)

    def test_fbank_shape_and_coverage(self):
        from paddle_tpu.audio import functional as AF
        fb = np.asarray(AF.compute_fbank_matrix(16000, 512, n_mels=40,
                                                norm=None)._value)
        assert fb.shape == (40, 257)
        assert fb.min() >= 0
        # every filter has support, triangles peak at 1 without norm
        assert (fb.max(axis=1) > 0.5).all()

    def test_spectrogram_matches_stft_power(self):
        x = np.random.RandomState(0).randn(2, 2000).astype("f4")
        spec = paddle.audio.features.Spectrogram(n_fft=256, hop_length=128)
        out = np.asarray(spec(paddle.to_tensor(x))._value)
        ref = paddle.signal.stft(paddle.to_tensor(x), 256, 128,
                                 window=spec.window)
        ref = np.abs(np.asarray(ref._value)) ** 2
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_mfcc_pipeline_shapes(self):
        x = np.random.RandomState(1).randn(3, 4000).astype("f4")
        mfcc = paddle.audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=512,
                                          n_mels=40)
        out = mfcc(paddle.to_tensor(x))
        assert tuple(out.shape)[0] == 3 and tuple(out.shape)[1] == 13
        assert np.isfinite(np.asarray(out._value)).all()

    def test_logmel_top_db_caps_range(self):
        x = np.random.RandomState(2).randn(2000).astype("f4")
        lm = paddle.audio.features.LogMelSpectrogram(sr=16000, n_fft=256,
                                                     top_db=60.0)
        out = np.asarray(lm(paddle.to_tensor(x))._value)
        assert out.max() - out.min() <= 60.0 + 1e-4
