"""Block-level SOT graph breaks (VERDICT r4 #4).

Reference contract: python/paddle/jit/sot keeps compiled subgraphs
around an unsupported construct — one host interaction must not un-jit
the whole forward.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.sot import SegmentPlan


def _plan(sf):
    plans = [v for v in sf._cache.values() if isinstance(v, SegmentPlan)]
    assert len(plans) == 1, f"expected one SegmentPlan, got {sf._cache}"
    return plans[0]


class TestSegmentedBreak:
    def _make(self):
        @paddle.jit.to_static
        def f(x):
            y = x * 2.0
            k = int((y > 0.0).sum())     # host concretization: the break
            z = y + float(k)
            return z * 3.0
        return f

    def test_two_compiled_segments(self):
        f = self._make()
        x = paddle.to_tensor(np.array([1.0, -2.0, 3.0], "f4"))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out1 = f(x)
        assert any("segmented into 2 compiled blocks" in str(m.message)
                   for m in w), [str(m.message) for m in w]
        plan = _plan(f)
        assert plan.n_segments == 2      # prefix + suffix, NOT whole-eager
        # journal-run result is correct: y + count(y>0), times 3
        expect = (np.array([2.0, -4.0, 6.0]) + 2.0) * 3.0
        np.testing.assert_allclose(np.asarray(out1._value), expect,
                                   rtol=1e-6)

    def test_replay_hits_guard(self):
        f = self._make()
        x1 = paddle.to_tensor(np.array([1.0, -2.0, 3.0], "f4"))
        f(x1)
        plan = _plan(f)
        # same values, fresh tensor: host decision identical → replay
        x2 = paddle.to_tensor(np.array([1.0, -2.0, 3.0], "f4"))
        out = f(x2)
        assert plan.replays == 1 and plan.guard_misses == 0
        expect = (np.array([2.0, -4.0, 6.0]) + 2.0) * 3.0
        np.testing.assert_allclose(np.asarray(out._value), expect,
                                   rtol=1e-6)

    def test_guard_miss_falls_back_correctly(self):
        f = self._make()
        f(paddle.to_tensor(np.array([1.0, -2.0, 3.0], "f4")))
        plan = _plan(f)
        # all-negative input: int() sync reads 0 instead of 2 → miss →
        # whole-function eager for THIS call, still the right answer
        x = paddle.to_tensor(np.array([-1.0, -2.0, -3.0], "f4"))
        out = f(x)
        assert plan.guard_misses == 1 and plan.replays == 0
        expect = (np.array([-2.0, -4.0, -6.0]) + 0.0) * 3.0
        np.testing.assert_allclose(np.asarray(out._value), expect,
                                   rtol=1e-6)

    def test_gradients_flow_through_replay(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            @paddle.jit.to_static
            def forward(self, x):
                h = self.fc(x)
                k = int((h > 0.0).sum())
                return (h * float(1 + k)).sum()

        paddle.seed(3)
        net = Net()
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4).astype("f4"))
        net(x)                            # journal run
        plan = _plan(net.forward)
        loss = net(x)                     # replayed, same values
        assert plan.replays == 1
        loss.backward()
        g_static = np.asarray(net.fc.weight.grad.numpy())

        # eager reference on an identical net
        paddle.seed(3)
        ref = Net()
        h = ref.fc(x)
        k = int(np.asarray(((h > 0.0).sum())._value))
        loss_ref = (h * float(1 + k)).sum()
        loss_ref.backward()
        g_eager = np.asarray(ref.fc.weight.grad.numpy())
        np.testing.assert_allclose(g_static, g_eager, rtol=1e-5,
                                   atol=1e-6)

    def test_rng_refuses_segmentation(self):
        @paddle.jit.to_static
        def f(x):
            k = int((x > 0.0).sum())
            return x * paddle.rand(x.shape) + float(k)

        from paddle_tpu.jit import _GRAPH_BREAK
        x = paddle.to_tensor(np.array([0.5, -0.5], "f4"))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(x)
        assert _GRAPH_BREAK in f._cache.values()   # eager, not segmented

    def test_returned_arg_remapped_per_call(self):
        # code-review r5 regression: an arg returned unchanged (never
        # consumed by a segment) must be the CURRENT call's tensor, not
        # the first call's baked constant
        @paddle.jit.to_static
        def f(x, y):
            k = int((y > 0.0).sum())
            return x, y + float(k)

        x1 = paddle.to_tensor(np.array([1.0], "f4"))
        y = paddle.to_tensor(np.array([0.5], "f4"))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(x1, y)
        x2 = paddle.to_tensor(np.array([42.0], "f4"))
        out_x, out_y = f(x2, y)           # replayed (same y → guard hit)
        np.testing.assert_allclose(np.asarray(out_x._value), [42.0])

    def test_inplace_op_refuses_segmentation(self):
        # code-review r5 regression: the in-place rebind side effect is
        # invisible to the journal → must stay whole-function eager
        @paddle.jit.to_static
        def f(x):
            k = int((x > 0.0).sum())
            h = x * 2.0
            h.add_(1.0)
            return h + float(k)

        from paddle_tpu.jit import _GRAPH_BREAK
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = f(paddle.to_tensor(np.array([1.0, -1.0], "f4")))
        assert _GRAPH_BREAK in f._cache.values()
        np.testing.assert_allclose(np.asarray(out._value), [4.0, -0.0])

    def test_ndarray_arg_refuses_segmentation(self):
        # code-review r5 #1: raw array args can't be remapped per call —
        # must stay whole-function eager (which re-reads them correctly)
        @paddle.jit.to_static
        def f(x, w):
            k = int((x > 0.0).sum())
            return x * paddle.to_tensor(w) + float(k)

        from paddle_tpu.jit import _GRAPH_BREAK
        x = paddle.to_tensor(np.array([1.0, -1.0], "f4"))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out1 = f(x, np.full((2,), 10.0, "f4"))
        assert _GRAPH_BREAK in f._cache.values()
        out2 = f(x, np.full((2,), 99.0, "f4"))   # same spec key
        np.testing.assert_allclose(np.asarray(out2._value),
                                   [100.0, -98.0])

    def test_host_path_op_guarded_via_numpy_sync(self):
        # code-review r5 #2: a host-computing op (nms host path) reads
        # via np.asarray(Tensor) which journals a sync — changed inputs
        # must guard-miss, not replay stale indices
        from paddle_tpu.vision.ops import nms

        @paddle.jit.to_static
        def f(x, boxes, scores):
            k = int((x > 0.0).sum())        # the graph break
            keep = nms(boxes, 0.5, scores=scores)
            return x.sum() * 0.0 + scores[keep].sum() + float(k)

        rs = np.random.RandomState(0)
        xy = rs.rand(12, 2) * 50
        b1 = np.concatenate([xy, xy + rs.rand(12, 2) * 20 + 1],
                            1).astype("f4")
        s1 = rs.rand(12).astype("f4")
        x = paddle.to_tensor(np.array([1.0], "f4"))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(x, paddle.to_tensor(b1), paddle.to_tensor(s1))
        # different boxes/scores, same shapes: must NOT reuse plan
        xy2 = rs.rand(12, 2) * 50
        b2 = np.concatenate([xy2, xy2 + rs.rand(12, 2) * 20 + 1],
                            1).astype("f4")
        s2 = rs.rand(12).astype("f4")
        out = f(x, paddle.to_tensor(b2), paddle.to_tensor(s2))
        # golden: pure eager
        keep = nms(paddle.to_tensor(b2), 0.5,
                   scores=paddle.to_tensor(s2)).numpy()
        expect = s2[keep].sum() + 1.0
        np.testing.assert_allclose(float(np.asarray(out._value)), expect,
                                   rtol=1e-5)

    def test_sot_disabled_raises(self):
        @paddle.jit.to_static
        def f(x):
            return x + float(int((x > 0.0).sum()))

        paddle.jit.enable_sot(False)
        try:
            with pytest.raises(Exception):
                f(paddle.to_tensor(np.array([1.0], "f4")))
        finally:
            paddle.jit.enable_sot(True)
