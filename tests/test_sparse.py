"""paddle.sparse: COO/CSR tensors, ops, and sparse nn layers.

Reference analogues: test/legacy_test/test_sparse_*_op.py
(utils/conv/norm/matmul/softmax...).  Goldens are dense numpy computations
masked to the sparsity pattern.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _random_coo(shape, nnz, seed=0, dense_dims=0):
    rng = np.random.RandomState(seed)
    sparse_shape = shape[:len(shape) - dense_dims]
    flat = rng.choice(int(np.prod(sparse_shape)), size=nnz, replace=False)
    idx = np.stack(np.unravel_index(flat, sparse_shape)).astype("int32")
    vals = rng.randn(nnz, *shape[len(sparse_shape):]).astype("float32")
    return idx, vals


class TestCreationConversion:
    def test_coo_roundtrip(self):
        idx, vals = _random_coo((4, 5), 6)
        st = sparse.sparse_coo_tensor(idx, vals, (4, 5))
        dense = np.zeros((4, 5), "float32")
        dense[idx[0], idx[1]] = vals
        np.testing.assert_allclose(st.to_dense().numpy(), dense)
        assert st.nnz() == 6 and st.is_sparse_coo()

    def test_coo_duplicate_coalesce(self):
        idx = np.array([[0, 0, 1], [1, 1, 2]], "int32")
        vals = np.array([1.0, 2.0, 3.0], "float32")
        st = sparse.sparse_coo_tensor(idx, vals, (2, 3)).coalesce()
        dense = st.to_dense().numpy()
        assert dense[0, 1] == pytest.approx(3.0)
        assert dense[1, 2] == pytest.approx(3.0)

    def test_csr_roundtrip(self):
        crows = np.array([0, 2, 3, 3], "int32")
        cols = np.array([0, 2, 1], "int32")
        vals = np.array([1.0, 2.0, 3.0], "float32")
        st = sparse.sparse_csr_tensor(crows, cols, vals, (3, 3))
        ref = np.array([[1, 0, 2], [0, 3, 0], [0, 0, 0]], "float32")
        np.testing.assert_allclose(st.to_dense().numpy(), ref)

    def test_coo_csr_conversions(self):
        idx, vals = _random_coo((5, 7), 9, seed=1)
        coo = sparse.sparse_coo_tensor(idx, vals, (5, 7))
        csr = coo.to_sparse_csr()
        np.testing.assert_allclose(csr.to_dense().numpy(),
                                   coo.to_dense().numpy())
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(),
                                   coo.to_dense().numpy())

    def test_dense_values_dims(self):
        idx, vals = _random_coo((3, 4, 2), 5, seed=2, dense_dims=1)
        st = sparse.sparse_coo_tensor(idx, vals, (3, 4, 2))
        assert st.sparse_dim() == 2 and st.dense_dim() == 1
        dense = np.zeros((3, 4, 2), "float32")
        dense[idx[0], idx[1]] = vals
        np.testing.assert_allclose(st.to_dense().numpy(), dense)


class TestElementwise:
    def test_unary_ops(self):
        idx, vals = _random_coo((4, 4), 5, seed=3)
        st = sparse.sparse_coo_tensor(idx, vals, (4, 4))
        got = sparse.tanh(st)
        np.testing.assert_allclose(got.values().numpy(), np.tanh(vals),
                                   rtol=1e-6)
        got2 = sparse.scale(st, 2.0, 1.0)
        np.testing.assert_allclose(got2.values().numpy(), vals * 2 + 1,
                                   rtol=1e-6)

    def test_binary_same_pattern(self):
        idx, vals = _random_coo((4, 4), 5, seed=4)
        a = sparse.sparse_coo_tensor(idx, vals, (4, 4))
        b = sparse.sparse_coo_tensor(idx, vals * 2, (4, 4))
        got = sparse.add(a, b)
        np.testing.assert_allclose(got.to_dense().numpy(),
                                   a.to_dense().numpy() * 3, rtol=1e-6)

    def test_binary_mismatched_pattern_falls_back_dense(self):
        x = sparse.sparse_coo_tensor(np.array([[0], [0]], "int32"),
                                     np.array([1.0], "float32"), (2, 2))
        y = sparse.sparse_coo_tensor(np.array([[1], [1]], "int32"),
                                     np.array([2.0], "float32"), (2, 2))
        got = sparse.add(x, y)
        got_dense = got.numpy() if hasattr(got, "is_sparse_coo") \
            else np.asarray(got._value)
        np.testing.assert_allclose(got_dense,
                                   np.array([[1, 0], [0, 2]], "float32"))

    def test_grad_flows_to_values(self):
        idx, vals = _random_coo((3, 3), 4, seed=5)
        st = sparse.sparse_coo_tensor(idx, vals, (3, 3),
                                      stop_gradient=False)
        out = paddle.sum(sparse.square(st).to_dense())
        out.backward()
        np.testing.assert_allclose(st.grad.numpy(), 2 * vals, rtol=1e-5)


class TestMatmul:
    def test_coo_matmul_dense(self):
        idx, vals = _random_coo((4, 6), 8, seed=6)
        st = sparse.sparse_coo_tensor(idx, vals, (4, 6))
        y = np.random.RandomState(7).randn(6, 3).astype("float32")
        got = sparse.matmul(st, paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(got, st.to_dense().numpy() @ y, rtol=1e-5,
                                   atol=1e-5)

    def test_csr_matmul_grad(self):
        crows = np.array([0, 1, 3], "int32")
        cols = np.array([1, 0, 2], "int32")
        vals = np.array([2.0, 1.0, -1.0], "float32")
        st = sparse.sparse_csr_tensor(crows, cols, vals, (2, 3),
                                      stop_gradient=False)
        y = paddle.to_tensor(np.ones((3, 2), "float32"))
        y.stop_gradient = False
        out = sparse.matmul(st, y)
        paddle.sum(out).backward()
        # d(sum)/d(vals[e]) = sum_j y[col_e, j] = 2 for all-ones y
        np.testing.assert_allclose(st.grad.numpy(), np.full(3, 2.0))
        ref_dy = st.to_dense().numpy().T @ np.ones((2, 2), "float32")
        np.testing.assert_allclose(y.grad.numpy(), ref_dy)

    def test_masked_matmul(self):
        rng = np.random.RandomState(8)
        x = rng.randn(4, 5).astype("float32")
        y = rng.randn(5, 4).astype("float32")
        idx, _ = _random_coo((4, 4), 6, seed=9)
        mask = sparse.sparse_coo_tensor(idx, np.ones(6, "float32"), (4, 4))
        got = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        full = x @ y
        np.testing.assert_allclose(got.values().numpy(),
                                   full[idx[0], idx[1]], rtol=1e-5)

    def test_mv_addmm(self):
        idx, vals = _random_coo((3, 4), 5, seed=10)
        st = sparse.sparse_coo_tensor(idx, vals, (3, 4))
        v = np.random.RandomState(11).randn(4).astype("float32")
        np.testing.assert_allclose(sparse.mv(st, paddle.to_tensor(v)).numpy(),
                                   st.to_dense().numpy() @ v, rtol=1e-5)
        inp = np.ones((3, 2), "float32")
        y = np.random.RandomState(12).randn(4, 2).astype("float32")
        got = sparse.addmm(paddle.to_tensor(inp), st, paddle.to_tensor(y),
                           beta=0.5, alpha=2.0).numpy()
        np.testing.assert_allclose(
            got, 0.5 * inp + 2.0 * (st.to_dense().numpy() @ y), rtol=1e-5)


class TestSoftmax:
    def test_csr_softmax_matches_dense(self):
        crows = np.array([0, 2, 4], "int32")
        cols = np.array([0, 2, 1, 3], "int32")
        vals = np.array([1.0, 2.0, -1.0, 0.5], "float32")
        st = sparse.sparse_csr_tensor(crows, cols, vals, (2, 4))
        got = sparse.softmax(st)
        # dense ref: softmax over the nonzeros of each row
        r0 = np.exp([1.0, 2.0]) / np.exp([1.0, 2.0]).sum()
        r1 = np.exp([-1.0, 0.5]) / np.exp([-1.0, 0.5]).sum()
        np.testing.assert_allclose(got.values().numpy(),
                                   np.concatenate([r0, r1]).astype("float32"),
                                   rtol=1e-6)


class TestSparseNN:
    def test_subm_conv3d_matches_masked_dense(self):
        import paddle_tpu.sparse.nn as spnn
        rng = np.random.RandomState(13)
        shape = (1, 4, 4, 4, 2)   # NDHWC
        idx, vals = _random_coo(shape, 6, seed=13, dense_dims=1)
        st = sparse.sparse_coo_tensor(idx, vals, shape)
        conv = spnn.SubmConv3D(2, 3, kernel_size=3)
        out = conv(st)
        assert out.shape == [1, 4, 4, 4, 3]
        # submanifold: output sites == input sites
        np.testing.assert_array_equal(np.asarray(out._indices),
                                      np.asarray(st._indices))
        # values equal dense conv (stride1 same-pad) gathered at sites
        import jax, jax.numpy as jnp
        dense = st.to_dense().numpy()
        ref_full = jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight._value, (1, 1, 1),
            [(1, 1)] * 3,
            dimension_numbers=jax.lax.conv_dimension_numbers(
                dense.shape, conv.weight._value.shape,
                ("NDHWC", "DHWIO", "NDHWC")))
        ref_vals = np.asarray(ref_full)[tuple(np.asarray(st._indices))] + \
            np.asarray(conv.bias._value)
        np.testing.assert_allclose(out.values().numpy(), ref_vals, rtol=1e-4,
                                   atol=1e-5)

    def test_conv3d_output_sites_and_grad(self):
        import paddle_tpu.sparse.nn as spnn
        shape = (1, 4, 4, 4, 1)
        idx, vals = _random_coo(shape, 4, seed=14, dense_dims=1)
        st = sparse.sparse_coo_tensor(idx, vals, shape, stop_gradient=False)
        conv = spnn.Conv3D(1, 2, kernel_size=2, stride=2)
        out = conv(st)
        assert out.shape == [1, 2, 2, 2, 2]
        loss = paddle.sum(out.values())
        loss.backward()
        assert conv.weight.grad is not None
        assert st.grad is not None

    def test_sparse_batchnorm(self):
        import paddle_tpu.sparse.nn as spnn
        shape = (2, 3, 3, 3, 4)
        idx, vals = _random_coo(shape, 10, seed=15, dense_dims=1)
        st = sparse.sparse_coo_tensor(idx, vals, shape)
        bn = spnn.BatchNorm(4)
        bn.train()
        out = bn(st)
        v = out.values().numpy()
        np.testing.assert_allclose(v.mean(0), np.zeros(4), atol=1e-5)
        np.testing.assert_allclose(v.std(0), np.ones(4), atol=1e-2)

    def test_batchnorm_train_grad_through_stats(self):
        # sum(BN(v)) has ~zero gradient wrt v (mean subtraction cancels);
        # stats must be differentiated through, not treated as constants
        import paddle_tpu.sparse.nn as spnn
        shape = (1, 3, 3, 3, 2)
        idx, vals = _random_coo(shape, 8, seed=22, dense_dims=1)
        st = sparse.sparse_coo_tensor(idx, vals, shape, stop_gradient=False)
        bn = spnn.BatchNorm(2)
        bn.train()
        out = bn(st)
        paddle.sum(out.values()).backward()
        np.testing.assert_allclose(st.grad.numpy(), np.zeros_like(vals),
                                   atol=1e-4)

    def test_subm_conv2d_even_kernel_boundary(self):
        # even kernel: output grid must still equal input grid (asymmetric
        # same-padding); a site at the far corner must see its own window
        import paddle_tpu.sparse.nn as spnn
        import jax, jax.numpy as jnp
        idx = np.array([[0], [3], [3]], "int32")  # N,H,W site at (3,3)
        vals = np.ones((1, 2), "float32")
        st = sparse.sparse_coo_tensor(idx, vals, (1, 4, 4, 2))
        conv = spnn.SubmConv2D(2, 3, kernel_size=2, bias_attr=False)
        out = conv(st)
        w = np.asarray(conv.weight._value)  # [2,2,in,out]
        # with pad (0,1) both dims, output[3,3] window covers only (3,3)
        # through w[0,0]
        ref = vals[0] @ w[0, 0]
        np.testing.assert_allclose(out.values().numpy()[0], ref, rtol=1e-5)

    def test_maxpool_negative_values(self):
        # inactive voxels must NOT contribute 0 to the max
        import paddle_tpu.sparse.nn as spnn
        idx = np.array([[0], [0], [0], [0]], "int32")
        vals = np.array([[-1.0]], "float32")
        st = sparse.sparse_coo_tensor(idx, vals, (1, 2, 2, 2, 1))
        out = spnn.MaxPool3D(kernel_size=2, stride=2)(st)
        np.testing.assert_allclose(out.values().numpy(), [[-1.0]])

    def test_maxpool_overlapping_windows(self):
        # stride < kernel: one active voxel feeds several output windows
        import paddle_tpu.sparse.nn as spnn
        idx = np.array([[0], [2], [1], [1]], "int32")
        vals = np.ones((1, 1), "float32")
        st = sparse.sparse_coo_tensor(idx, vals, (1, 5, 3, 3, 1))
        pool = spnn.MaxPool3D(kernel_size=3, stride=1)
        out = pool(st)
        # output spatial (3,1,1); windows d=0,1,2 all cover input d=2
        assert out.nnz() == 3
        np.testing.assert_allclose(out.values().numpy(),
                                   np.ones((3, 1), "float32"))

    def test_relu_layer(self):
        import paddle_tpu.sparse.nn as spnn
        idx, vals = _random_coo((3, 3), 4, seed=16)
        st = sparse.sparse_coo_tensor(idx, vals, (3, 3))
        out = spnn.ReLU()(st)
        np.testing.assert_allclose(out.values().numpy(),
                                   np.maximum(vals, 0))

    def test_maxpool3d(self):
        import paddle_tpu.sparse.nn as spnn
        shape = (1, 4, 4, 4, 1)
        idx, vals = _random_coo(shape, 5, seed=17, dense_dims=1)
        vals = np.abs(vals) + 0.1  # positive so max over window is a site
        st = sparse.sparse_coo_tensor(idx, vals, shape)
        pool = spnn.MaxPool3D(kernel_size=2, stride=2)
        out = pool(st)
        assert out.shape == [1, 2, 2, 2, 1]
        dense_ref = st.to_dense().numpy().reshape(1, 2, 2, 2, 2, 2, 2, 1)
        # windows with a site must match dense pooling at those coords
        got_dense = np.zeros((1, 2, 2, 2, 1), "float32")
        oc = np.asarray(out._indices)
        got_dense[oc[0], oc[1], oc[2], oc[3]] = out.values().numpy()
        ref = st.to_dense().numpy()
        for b, d, h, w in zip(*[oc[i] for i in range(4)]):
            win = ref[b, 2*d:2*d+2, 2*h:2*h+2, 2*w:2*w+2, 0]
            assert got_dense[b, d, h, w, 0] == pytest.approx(win.max())


class TestTransforms:
    def test_transpose(self):
        idx, vals = _random_coo((3, 5), 6, seed=18)
        st = sparse.sparse_coo_tensor(idx, vals, (3, 5))
        got = sparse.transpose(st, [1, 0])
        np.testing.assert_allclose(got.to_dense().numpy(),
                                   st.to_dense().numpy().T)

    def test_reshape(self):
        idx, vals = _random_coo((4, 6), 7, seed=19)
        st = sparse.sparse_coo_tensor(idx, vals, (4, 6))
        got = sparse.reshape(st, [2, -1])
        np.testing.assert_allclose(got.to_dense().numpy(),
                                   st.to_dense().numpy().reshape(2, 12))

    def test_sum(self):
        idx, vals = _random_coo((4, 6), 7, seed=20)
        st = sparse.sparse_coo_tensor(idx, vals, (4, 6))
        np.testing.assert_allclose(sparse.sum(st).numpy(), vals.sum(),
                                   rtol=1e-5)
        np.testing.assert_allclose(sparse.sum(st, axis=1).numpy(),
                                   st.to_dense().numpy().sum(1), rtol=1e-5)

    def test_attention(self):
        import paddle_tpu.sparse.nn as spnn
        rng = np.random.RandomState(21)
        q = rng.randn(4, 8).astype("float32")
        k = rng.randn(4, 8).astype("float32")
        v = rng.randn(4, 8).astype("float32")
        # full mask → must equal dense attention
        ii, jj = np.meshgrid(np.arange(4), np.arange(4), indexing="ij")
        idx = np.stack([ii.ravel(), jj.ravel()]).astype("int32")
        mask = sparse.sparse_coo_tensor(idx, np.ones(16, "float32"), (4, 4))
        got = spnn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            mask).numpy()
        scores = (q @ k.T) / np.sqrt(8)
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        np.testing.assert_allclose(got, probs @ v, rtol=1e-4, atol=1e-5)

    def test_attention_key_padding_mask(self):
        import paddle_tpu.sparse.nn as spnn
        rng = np.random.RandomState(22)
        q = rng.randn(3, 4).astype("float32")
        k = rng.randn(3, 4).astype("float32")
        v = rng.randn(3, 4).astype("float32")
        ii, jj = np.meshgrid(np.arange(3), np.arange(3), indexing="ij")
        idx = np.stack([ii.ravel(), jj.ravel()]).astype("int32")
        mask = sparse.sparse_coo_tensor(idx, np.ones(9, "float32"), (3, 3))
        kp = np.array([1, 1, 0], "float32")   # key 2 is padding
        got = spnn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            mask, key_padding_mask=paddle.to_tensor(kp)).numpy()
        scores = (q @ k.T) / np.sqrt(4)
        scores[:, 2] = -1e9
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        np.testing.assert_allclose(got, probs @ v, rtol=1e-4, atol=1e-5)

    def test_sum_dtype_with_axis(self):
        idx, vals = _random_coo((4, 6), 7, seed=23)
        st = sparse.sparse_coo_tensor(idx, vals, (4, 6))
        out = sparse.sum(st, axis=1, dtype="float64")
        assert str(out.numpy().dtype) in ("float64", "float32")  # x64 off→f32
        out2 = sparse.sum(st, axis=1, dtype="int32")
        assert str(out2.numpy().dtype) == "int32"
