"""paddle.distributed.spawn: real multi-process fork with rank env.

Reference analogue: test/legacy_test/test_spawn_and_init_parallel_env.py.
"""
import os

import pytest

from paddle_tpu.distributed import spawn


def _write_rank(out_dir):
    # runs in the child: rank env must be set before user code
    rank = os.environ["PADDLE_TRAINER_ID"]
    world = os.environ["PADDLE_TRAINERS_NUM"]
    with open(os.path.join(out_dir, f"rank{rank}.txt"), "w") as f:
        f.write(f"{rank}/{world}/{os.environ['PADDLE_MASTER']}")


def _fail():
    raise SystemExit(3)


class TestSpawn:
    def test_inline_single(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        spawn(_write_rank, args=(str(tmp_path),), nprocs=1)
        assert (tmp_path / "rank0.txt").exists()

    def test_two_workers(self, tmp_path):
        ctx = spawn(_write_rank, args=(str(tmp_path),), nprocs=2)
        files = sorted(os.listdir(tmp_path))
        assert files == ["rank0.txt", "rank1.txt"]
        r0 = (tmp_path / "rank0.txt").read_text()
        r1 = (tmp_path / "rank1.txt").read_text()
        assert r0.startswith("0/2/") and r1.startswith("1/2/")
        # both ranks saw the same master endpoint
        assert r0.split("/")[2:] == r1.split("/")[2:]

    def test_failure_propagates(self):
        with pytest.raises(RuntimeError, match="exited with code 3"):
            spawn(_fail, nprocs=2)
