"""Speculative decoding (inference/speculative.py + the spec mode of
inference/serving.py): bitwise greedy parity vs generate() across the
three causal-LM families on dense and paged engines, acceptance-length
bookkeeping, n-gram prompt-lookup drafter correctness, build-time
draft/target validation — plus the PR's selective-remat satellite
(GPTConfig.remat_policy lowering to jax.checkpoint policies).

The parity tests are the subsystem's core claim: greedy verification
accepts a draft token only when it EQUALS the target's argmax for that
prefix, so the emitted stream is the target's own greedy stream token
for token, bit for bit — whatever the drafter proposes.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework import guardian
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.inference.speculative import (SpecConfig,
                                              build_ngram_drafter,
                                              speculative_generate)
from paddle_tpu.models import (GPTForPretraining, LlamaForCausalLM,
                               gpt3_tiny, llama_tiny)

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(0)
    return GPTForPretraining(gpt3_tiny())


@pytest.fixture(scope="module")
def llama():
    paddle.seed(0)
    net = LlamaForCausalLM(llama_tiny())
    rng = np.random.RandomState(3)
    for _, p in net.named_parameters():
        if len(p.shape) >= 2:
            p._value = jnp.asarray(
                rng.normal(0, 0.05, tuple(p.shape)).astype("float32"))
    return net


@pytest.fixture(scope="module")
def draft_gpt():
    """Smaller same-family, same-vocab draft for the two-model path."""
    paddle.seed(11)
    from paddle_tpu.models import GPTConfig
    return GPTForPretraining(GPTConfig(
        vocab_size=1024, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, max_position_embeddings=128))


def _gen(net, prompt_np, n, **kw):
    if prompt_np.ndim == 1:
        prompt_np = prompt_np[None, :]
    ids, _ = net.generate(paddle.to_tensor(prompt_np), max_new_tokens=n,
                          **kw)
    return np.asarray(ids._value)


def _run_all(eng, prompts, budgets):
    reqs = [eng.submit(p, int(b)) for p, b in zip(prompts, budgets)]
    eng.run()
    return reqs


class TestStandaloneParity:
    def test_ngram_ids_bitwise_scores_close(self, gpt):
        """speculative_generate == generate greedy: ids BITWISE, scores
        to one ulp (the width-γ+1 verify recomputes the same logit rows
        under a different XLA reduction order)."""
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 1024, (3, 12)).astype("int32")
        ref, ref_sc = gpt.generate(paddle.to_tensor(ids),
                                   max_new_tokens=16)
        got, got_sc = speculative_generate(gpt, ids, max_new_tokens=16,
                                           gamma=4, ngram=2)
        np.testing.assert_array_equal(np.asarray(ref._value),
                                      np.asarray(got._value))
        np.testing.assert_allclose(np.asarray(ref_sc._value),
                                   np.asarray(got_sc._value),
                                   rtol=0, atol=2e-6)

    def test_draft_model_ids_bitwise(self, gpt, draft_gpt):
        rng = np.random.RandomState(2)
        ids = rng.randint(0, 1024, (2, 9)).astype("int32")
        ref = _gen(gpt, ids, 12)
        got, _ = speculative_generate(gpt, ids, max_new_tokens=12,
                                      draft_model=draft_gpt, gamma=3)
        np.testing.assert_array_equal(ref, np.asarray(got._value))

    def test_eos_and_padding_bitwise(self, gpt):
        """eos mid-stream: the emitted prefix stops at eos and the tail
        is pad — exactly generate()'s masked-finish output."""
        rng = np.random.RandomState(3)
        ids = rng.randint(0, 1024, (2, 8)).astype("int32")
        free = _gen(gpt, ids, 12)
        eos = int(free[0, 4])
        ref = _gen(gpt, ids, 12, eos_token_id=eos)
        got, _ = speculative_generate(gpt, ids, max_new_tokens=12,
                                      gamma=4, ngram=2, eos_token_id=eos)
        np.testing.assert_array_equal(ref, np.asarray(got._value))

    def test_single_token_budget(self, gpt):
        rng = np.random.RandomState(4)
        ids = rng.randint(0, 1024, (2, 6)).astype("int32")
        ref = _gen(gpt, ids, 1)
        got, _ = speculative_generate(gpt, ids, max_new_tokens=1)
        np.testing.assert_array_equal(ref, np.asarray(got._value))

    def test_mixin_entry(self, gpt):
        rng = np.random.RandomState(5)
        ids = rng.randint(0, 1024, (1, 7)).astype("int32")
        got, _ = gpt.speculative_generate(ids, max_new_tokens=6)
        np.testing.assert_array_equal(_gen(gpt, ids, 6),
                                      np.asarray(got._value))


class TestEngineParity:
    def test_gpt_dense_and_paged_bitwise(self, gpt):
        """The acceptance gate: spec engine output == generate() bitwise
        on both KV modes, ragged prompts and budgets."""
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 1024, (n,)).astype("int32")
                   for n in (5, 11, 8, 3)]
        for kw in ({}, {"kv_mode": "paged", "page_size": 8}):
            eng = ServingEngine(gpt, num_slots=2, chunk=4,
                                prefill_buckets=(8, 16),
                                spec_decode=SpecConfig(gamma=3, ngram=2),
                                **kw)
            reqs = _run_all(eng, prompts, [9, 6, 9, 4])
            for p, b, r in zip(prompts, [9, 6, 9, 4], reqs):
                np.testing.assert_array_equal(
                    np.asarray(r.tokens, np.int32), _gen(gpt, p, b)[0])
            if eng._kv is not None:
                eng._kv.check()

    def test_llama_paged_spec_bitwise(self, llama):
        """The family whose cached attention differs most (rope + GQA)
        through the paged spec chunk."""
        rng = np.random.RandomState(6)
        prompts = [rng.randint(0, 512, (n,)).astype("int32")
                   for n in (5, 9)]
        eng = ServingEngine(llama, num_slots=2, chunk=4,
                            prefill_buckets=(16,), kv_mode="paged",
                            page_size=8,
                            spec_decode=SpecConfig(gamma=3, ngram=2))
        reqs = _run_all(eng, prompts, [7, 4])
        for p, b, r in zip(prompts, [7, 4], reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(llama, p, b)[0])
        eng._kv.check()

    def test_gpt_moe_dense_spec_bitwise(self):
        """Third family: MoE routing competes capacity among the γ+1
        verify tokens, so capacity is lifted to never bind (the causal-
        consistency caveat generate() documents)."""
        from paddle_tpu.models import GPTMoEForPretraining, gpt_moe_tiny
        paddle.seed(0)
        cfg = gpt_moe_tiny(num_hidden_layers=2)
        moe = GPTMoEForPretraining(cfg)
        for m in moe.gpt.moe_layers():
            m.gate.capacity_factor = float(cfg.num_experts * cfg.top_k)
        rng = np.random.RandomState(3)
        p = rng.randint(0, 1024, (6,)).astype("int32")
        eng = ServingEngine(moe, num_slots=1, chunk=4,
                            prefill_buckets=(8,),
                            spec_decode=SpecConfig(gamma=3, ngram=2))
        (r,) = _run_all(eng, [p], [5])
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      _gen(moe, p, 5)[0])

    def test_draft_model_engine_bitwise(self, gpt, draft_gpt):
        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, 1024, (n,)).astype("int32")
                   for n in (5, 8)]
        eng = ServingEngine(
            gpt, num_slots=2, chunk=4, prefill_buckets=(8, 16),
            spec_decode=SpecConfig(gamma=3, draft_model=draft_gpt))
        reqs = _run_all(eng, prompts, [8, 8])
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, 8)[0])

    def test_small_budget_tight_pool_admits(self, gpt):
        """Review regression: spec admission must plan budget+gamma
        write tokens (pos advances only by committed tokens; the final
        step overhangs by at most gamma), NOT budget*(gamma+1) — the
        over-demand made a resumable small-budget request that submit()
        accepted hard-fail admission on an exactly-sized pool."""
        rng = np.random.RandomState(14)
        p = rng.randint(0, 1024, (16,)).astype("int32")
        # 3 allocatable pages of 16 = 48 tokens; true extent is
        # 16 + 8 + 4 = 28 (2 pages); the old budget*(gamma+1) plan
        # demanded 16 + 40 = 56 (4 pages) and raised
        eng = ServingEngine(gpt, num_slots=1, chunk=32, max_seq_len=64,
                            prefill_buckets=(16, 32), kv_mode="paged",
                            page_size=16, num_pages=4,
                            spec_decode=SpecConfig(gamma=4, ngram=2))
        (r,) = _run_all(eng, [p], [8])
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      _gen(gpt, p, 8)[0])
        eng._kv.check()

    def test_paged_int8_spec_runs_and_agrees(self, gpt):
        """Speculation composes with int8 KV.  int8 is tolerance-
        bounded, not bitwise (docs/serving.md) — and under speculation
        the verify window's keys are EXACT (in-buffer) where sequential
        int8 re-reads them quantized, so spec-vs-nonspec tokens may
        legitimately differ at near-ties.  Assert the run completes its
        budgets with sane acceptance and high token agreement."""
        rng = np.random.RandomState(13)
        p = rng.randint(0, 1024, (6,)).astype("int32")
        outs = []
        for spec in (None, SpecConfig(gamma=3, ngram=2)):
            eng = ServingEngine(gpt, num_slots=1, chunk=4,
                                prefill_buckets=(8,), kv_mode="paged",
                                page_size=8, kv_dtype="int8",
                                spec_decode=spec)
            (r,) = _run_all(eng, [p], [8])
            assert len(r.tokens) == 8
            outs.append(list(r.tokens))
            eng._kv.check()
        agree = sum(a == b for a, b in zip(*outs)) / 8
        assert agree >= 0.75, outs

    def test_eviction_resume_bitwise(self, gpt):
        """Page pressure under speculation: per-slot lengths rewind,
        pages stay reserved, and a preempted request resumes by
        recompute — output still bitwise equal to generate()."""
        rng = np.random.RandomState(8)
        prompts = [rng.randint(0, 1024, (n,)).astype("int32")
                   for n in (6, 7, 5)]
        eng = ServingEngine(
            gpt, num_slots=3, chunk=4, prefill_buckets=(8, 16),
            kv_mode="paged", page_size=4, num_pages=13,
            spec_decode=SpecConfig(gamma=3, ngram=2, steps=1))
        reqs = _run_all(eng, prompts, [10, 10, 10])
        assert eng.stats["page_evictions"] > 0   # pressure actually hit
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), _gen(gpt, p, 10)[0])
        eng._kv.check()


class TestAcceptanceBookkeeping:
    def test_stats_identity_and_events(self, gpt):
        """decoded_tokens must reconcile exactly with the acceptance
        ledger: one first token per admission, plus one committed token
        per slot-verify-step, plus the accepted drafts — and the
        serving_spec_accept guardian event mirrors the same counters."""
        guardian.clear_events()
        rng = np.random.RandomState(9)
        prompts = [rng.randint(0, 1024, (n,)).astype("int32")
                   for n in (5, 9)]
        eng = ServingEngine(gpt, num_slots=2, chunk=4,
                            prefill_buckets=(16,),
                            spec_decode=SpecConfig(gamma=3, ngram=2))
        reqs = _run_all(eng, prompts, [10, 7])
        s = eng.stats
        assert s["decoded_tokens"] == 17
        participations = s["spec_proposed"] // 3
        assert s["spec_proposed"] % 3 == 0
        assert 0 <= s["spec_accepted"] <= s["spec_proposed"]
        assert s["decoded_tokens"] == \
            len(reqs) + participations + s["spec_accepted"]
        assert s["spec_chunks"] == s["chunks"] > 0
        assert s["spec_verify_steps"] >= s["spec_chunks"]
        # per-request ledgers sum to the engine's
        assert sum(r.spec_proposed for r in reqs) == s["spec_proposed"]
        assert sum(r.spec_accepted for r in reqs) == s["spec_accepted"]
        (ev,) = guardian.events("serving_spec_accept")
        assert ev["proposed"] == s["spec_proposed"]
        assert ev["accepted"] == s["spec_accepted"]
        assert ev["verify_steps"] == s["spec_verify_steps"]
        assert ev["gamma"] == 3

    def test_spec_metrics_recorded(self, gpt):
        from paddle_tpu import observability as obs
        obs.get_registry().reset()
        rng = np.random.RandomState(10)
        eng = ServingEngine(gpt, num_slots=1, chunk=4,
                            prefill_buckets=(8,),
                            spec_decode=SpecConfig(gamma=2, ngram=2))
        _run_all(eng, [rng.randint(0, 1024, (5,)).astype("int32")], [8])
        reg = obs.get_registry()
        prop = reg.get("pt_serving_spec_proposed_total")
        acc = reg.get("pt_serving_spec_accepted_total")
        assert prop is not None and prop.value() == \
            eng.stats["spec_proposed"] > 0
        assert (acc.value() if acc is not None else 0) == \
            eng.stats["spec_accepted"]
        assert reg.get("pt_serving_spec_draft_chunks_total").value() == \
            eng.stats["spec_chunks"]
        hist = reg.get("pt_serving_spec_accept_len")
        assert hist is not None and \
            hist.count() == eng.stats["spec_proposed"] // 2


class TestNgramDrafter:
    def test_lookup_continues_most_recent_match(self):
        """History ...a b c a b -> with ngram=2 and current token b at
        pos, the drafter must propose the continuation after the most
        recent EARLIER (a, b): c, then a, then b (clamped to known)."""
        MAX = 16
        draft = build_ngram_drafter(3, 2, MAX)
        a, b, c = 7, 8, 9
        hist = np.zeros((1, MAX), np.int32)
        seq = [a, b, c, a, b]                   # pos = 4, current = b
        hist[0, :5] = seq
        out = jax.jit(draft)(jnp.asarray(hist),
                             jnp.asarray([b], jnp.int32),
                             jnp.asarray([4], jnp.int32))
        got = np.asarray(out)[0]
        # match at j=0 (a b), continuation hist[2:5] = c a b
        np.testing.assert_array_equal(got, [c, a, b])

    def test_no_match_repeats_current(self):
        MAX = 16
        draft = build_ngram_drafter(2, 2, MAX)
        hist = np.zeros((1, MAX), np.int32)
        hist[0, :4] = [1, 2, 3, 4]
        out = jax.jit(draft)(jnp.asarray(hist),
                             jnp.asarray([4], jnp.int32),
                             jnp.asarray([3], jnp.int32))
        np.testing.assert_array_equal(np.asarray(out)[0], [4, 4])

    def test_constant_run_fully_accepted(self):
        """A constant tail must draft the constant — the degenerate
        regime greedy decode settles into, where speculation pays."""
        MAX = 16
        draft = build_ngram_drafter(4, 2, MAX)
        hist = np.zeros((1, MAX), np.int32)
        hist[0, :6] = [3, 5, 5, 5, 5, 5]        # pos=5, current=5
        out = jax.jit(draft)(jnp.asarray(hist),
                             jnp.asarray([5], jnp.int32),
                             jnp.asarray([5], jnp.int32))
        np.testing.assert_array_equal(np.asarray(out)[0], [5, 5, 5, 5])


class TestBuildTimeValidation:
    def test_vocab_mismatch_raises(self, gpt, llama):
        with pytest.raises(ValueError, match="vocab_size"):
            ServingEngine(gpt, spec_decode=SpecConfig(draft_model=llama))
        with pytest.raises(ValueError, match="vocab_size"):
            speculative_generate(gpt, np.zeros((1, 4), np.int32),
                                 max_new_tokens=4, draft_model=llama)

    def test_bad_gamma_and_steps_raise(self, gpt):
        with pytest.raises(ValueError, match="gamma"):
            ServingEngine(gpt, spec_decode=SpecConfig(gamma=0))
        with pytest.raises(ValueError, match="steps"):
            ServingEngine(gpt, spec_decode=SpecConfig(steps=0))

    def test_short_draft_position_table_raises(self, gpt):
        from paddle_tpu.models import GPTConfig
        paddle.seed(12)
        shorty = GPTForPretraining(GPTConfig(
            vocab_size=1024, hidden_size=32, num_hidden_layers=1,
            num_attention_heads=2, max_position_embeddings=16))
        with pytest.raises(ValueError, match="max_position_embeddings"):
            ServingEngine(gpt, max_seq_len=64,
                          spec_decode=SpecConfig(draft_model=shorty))


class TestRematPolicy:
    """PR satellite: GPTConfig.remat widened to remat_policy (a
    jax.checkpoint_policies name) — selective remat must not change the
    math, and an unknown policy must fail loudly at build."""

    def _grads(self, remat=False, policy=None):
        from paddle_tpu.framework import autograd as _ag
        from paddle_tpu.framework.random import rng_scope
        from paddle_tpu.models import GPTConfig
        cfg = GPTConfig(vocab_size=512, hidden_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=64, remat=remat,
                        remat_policy=policy)
        paddle.seed(0)
        net = GPTForPretraining(cfg)
        net.eval()
        params = [p for _, p in net.named_parameters()]
        pvals = [p._value for p in params]
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, 512, (2, 16)).astype("int32"))

        def loss_fn(pv):
            olds = [p._value for p in params]
            for p, v in zip(params, pv):
                p._value = v
            try:
                with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                    lg = net(paddle.Tensor(ids))._value
                lp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
                return -jnp.take_along_axis(
                    lp[:, :-1], ids[:, 1:, None], 2).mean()
            finally:
                for p, v in zip(params, olds):
                    p._value = v
        loss, g = jax.jit(jax.value_and_grad(loss_fn))(pvals)
        return float(loss), [np.asarray(x) for x in g]

    def test_policy_matches_full_remat_and_baseline(self):
        l0, g0 = self._grads()
        l1, g1 = self._grads(policy="dots_saveable")
        l2, g2 = self._grads(remat=True)
        assert l0 == pytest.approx(l1, rel=1e-6)
        for a, b in zip(g0, g1):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
        # a policy is just selective saving: identical to full remat
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError, match="remat_policy"):
            self._grads(policy="definitely_not_a_policy")

    def test_llama_config_has_knob(self):
        from paddle_tpu.models import LlamaConfig
        assert LlamaConfig(remat_policy="dots_saveable").remat_policy \
            == "dots_saveable"
