"""Static-graph mode: Program recording, Executor replay, inference model
save/load.

Reference analogues: test/legacy_test/test_executor_*.py,
test_inference_model_io.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_linear_program():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        net = nn.Linear(4, 2)
        pred = net(x)
        out = paddle.tanh(pred)
    return main, x, net, pred, out


class TestStaticProgram:
    def test_mode_toggles(self):
        assert not paddle.in_dynamic_mode()
        paddle.disable_static()
        assert paddle.in_dynamic_mode()
        paddle.enable_static()

    def test_ops_recorded(self):
        main, x, net, pred, out = _build_linear_program()
        assert main.num_ops >= 2          # linear (+bias) + tanh
        assert "x" in main._placeholders

    def test_program_guard_isolation(self):
        p1 = static.Program()
        with static.program_guard(p1):
            static.data("a", [2, 2])
        assert "a" in p1._placeholders
        assert "a" not in static.default_main_program()._placeholders


class TestExecutor:
    def test_run_matches_eager(self):
        main, x, net, pred, out = _build_linear_program()
        exe = static.Executor()
        xs = np.random.RandomState(0).randn(8, 4).astype("float32")
        got_pred, got_out = exe.run(main, feed={"x": xs},
                                    fetch_list=[pred, out])
        w = np.asarray(net.weight._value)
        b = np.asarray(net.bias._value)
        ref = xs @ w + b
        np.testing.assert_allclose(got_pred, ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got_out, np.tanh(ref), rtol=1e-5,
                                   atol=1e-6)

    def test_feed_batch_differs_from_placeholder(self):
        # placeholder stand-in is batch 1; feeding batch 32 must work
        main, x, net, pred, out = _build_linear_program()
        exe = static.Executor()
        xs = np.ones((32, 4), "float32")
        (got,) = exe.run(main, feed={"x": xs}, fetch_list=[pred])
        assert got.shape == (32, 2)

    def test_param_update_visible_without_retrace(self):
        main, x, net, pred, out = _build_linear_program()
        exe = static.Executor()
        xs = np.ones((4, 4), "float32")
        (o1,) = exe.run(main, feed={"x": xs}, fetch_list=[pred])
        import jax.numpy as jnp
        net.weight._value = net.weight._value + 1.0   # optimizer-style rebind
        (o2,) = exe.run(main, feed={"x": xs}, fetch_list=[pred])
        np.testing.assert_allclose(o2 - o1, np.full((4, 2), 4.0), rtol=1e-5)

    def test_missing_feed_raises(self):
        main, x, net, pred, out = _build_linear_program()
        exe = static.Executor()
        with pytest.raises(ValueError, match="missing feeds"):
            exe.run(main, feed={}, fetch_list=[pred])


class TestReviewRegressions:
    def test_fetch_unrecorded_raises(self):
        # building without static mode → no ops recorded → loud error
        paddle.disable_static()
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4])
            net = nn.Linear(4, 2)
            pred = net(x)          # NOT recorded (dynamic mode)
        paddle.enable_static()
        # re-record one dummy op so the program is non-empty
        with static.program_guard(main):
            y = paddle.tanh(x)
        exe = static.Executor()
        with pytest.raises(ValueError, match="not produced"):
            exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                    fetch_list=[pred])

    def test_jit_trace_does_not_pollute_program(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4])
            out = paddle.tanh(x)
        n_before = main.num_ops
        # a jit trace while static mode is on must not record tracer ops
        import jax
        from paddle_tpu.framework import autograd as _ag
        from paddle_tpu.framework.core import Tensor

        def vf(v):
            with _ag.suspend_tape():
                return paddle.exp(Tensor(v))._value
        jax.jit(vf)(np.ones(3, "float32"))
        assert main.num_ops == n_before
        exe = static.Executor()
        (got,) = exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                         fetch_list=[out])
        np.testing.assert_allclose(got, np.zeros((2, 4)), atol=1e-6)

    def test_save_prunes_dead_placeholders(self, tmp_path):
        # label placeholder feeds only the loss; exporting pred must not
        # bind x's feed to the label slot
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [1, 4])
            label = static.data("label", [1, 2])
            net = nn.Linear(4, 2)
            pred = net(x)
            loss = paddle.mean((pred - label) ** 2)  # noqa: F841
        exe = static.Executor()
        prefix = str(tmp_path / "pruned")
        static.save_inference_model(prefix, [x], [pred], exe, program=main)
        xs = np.random.RandomState(2).randn(1, 4).astype("float32")
        (ref,) = exe.run(main, feed={"x": xs,
                                     "label": np.zeros((1, 2), "float32")},
                         fetch_list=[pred])
        loaded, feed_names, _ = static.load_inference_model(prefix, exe)
        assert feed_names == ["x"]
        (got,) = loaded.run({"x": xs})
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


class TestInferenceModelIO:
    def test_save_load_roundtrip(self, tmp_path):
        main, x, net, pred, out = _build_linear_program()
        exe = static.Executor()
        prefix = str(tmp_path / "inf")
        static.save_inference_model(prefix, [x], [out], exe, program=main)
        xs = np.random.RandomState(1).randn(1, 4).astype("float32")
        (ref,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
        loaded, feed_names, fetches = static.load_inference_model(prefix, exe)
        assert feed_names == ["x"]
        (got,) = loaded.run({"x": xs})
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


class TestStaticNNBuilders:
    """Legacy static.nn layer builders (reference: static.nn.fc etc.)."""

    def test_fc_runs_and_caches_params(self):
        import paddle_tpu as paddle
        from paddle_tpu import static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main, static.Program()):
                x = static.data("x", [None, 4], "float32")
                h = static.nn.fc(x, 8, activation="relu", name="h")
                out = static.nn.fc(h, 2, name="o")
            exe = static.Executor()
            feed = {"x": np.random.RandomState(0).rand(5, 4).astype("f4")}
            r1 = exe.run(main, feed=feed, fetch_list=[out])
            r2 = exe.run(main, feed=feed, fetch_list=[out])
            assert r1[0].shape == (5, 2)
            np.testing.assert_allclose(r1[0], r2[0])  # same cached params
        finally:
            paddle.disable_static()

    def test_conv_bn_pipeline(self):
        import paddle_tpu as paddle
        from paddle_tpu import static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main, static.Program()):
                x = static.data("img", [None, 3, 8, 8], "float32")
                c = static.nn.conv2d(x, 4, 3, padding=1, name="c1")
                b = static.nn.batch_norm(c, act="relu", is_test=True,
                                         name="bn1")
                ln = static.nn.layer_norm(b, begin_norm_axis=1, name="ln1")
            exe = static.Executor()
            r = exe.run(main,
                        feed={"img": np.random.RandomState(1)
                              .rand(2, 3, 8, 8).astype("f4")},
                        fetch_list=[ln])
            assert r[0].shape == (2, 4, 8, 8)
            assert np.isfinite(r[0]).all()
        finally:
            paddle.disable_static()

    def test_embedding_builder(self):
        import paddle_tpu as paddle
        from paddle_tpu import static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main, static.Program()):
                ids = static.data("ids", [None, 6], "int64")
                emb = static.nn.embedding(ids, size=[32, 8], name="emb")
            exe = static.Executor()
            r = exe.run(main,
                        feed={"ids": np.random.RandomState(2)
                              .randint(0, 32, (3, 6)).astype("i8")},
                        fetch_list=[emb])
            assert r[0].shape == (3, 6, 8)
        finally:
            paddle.disable_static()


def test_static_nn_round4_surface():
    """switch_case (concrete + traced lax.switch), case, static_pylayer
    custom vjp, and the norm/prelu/bilinear/spectral wrappers."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.framework.core import Tensor
    sn = paddle.static.nn

    out = jax.jit(lambda i: sn.switch_case(
        Tensor(i), {1: lambda: Tensor(jnp.asarray(10.0)),
                    5: lambda: Tensor(jnp.asarray(50.0))},
        default=lambda: Tensor(jnp.asarray(-1.0)))._value)
    assert float(out(jnp.asarray(5))) == 50.0
    assert float(out(jnp.asarray(3))) == -1.0

    r = sn.case([(paddle.to_tensor(np.asarray(False)),
                  lambda: paddle.to_tensor(np.asarray(1.0))),
                 (paddle.to_tensor(np.asarray(True)),
                  lambda: paddle.to_tensor(np.asarray(2.0)))],
                default=lambda: paddle.to_tensor(np.asarray(3.0)))
    assert float(r._value) == 2.0

    x = paddle.to_tensor(np.asarray([1.0, 2.0], "f4"),
                         stop_gradient=False)
    out2 = sn.static_pylayer(lambda a: a * 2.0, [x],
                             backward_fn=lambda g: g * 7.0)
    out2.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0, 7.0])
    # no backward_fn -> gradients blocked
    x2 = paddle.to_tensor(np.asarray([1.0], "f4"), stop_gradient=False)
    out3 = sn.static_pylayer(lambda a: a * 3.0, [x2])
    assert float(out3._value[0]) == 3.0

    img = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 4, 8, 8).astype("f4"))
    assert tuple(sn.group_norm(img, groups=2).shape) == (2, 4, 8, 8)
    assert tuple(sn.instance_norm(img).shape) == (2, 4, 8, 8)
    assert tuple(sn.prelu(img).shape) == (2, 4, 8, 8)
    w = paddle.to_tensor(np.random.RandomState(1).randn(6, 4).astype("f4"))
    s_max = np.linalg.svd(sn.spectral_norm(w, power_iters=3).numpy(),
                          compute_uv=False)[0]
    assert abs(s_max - 1.0) < 0.2
