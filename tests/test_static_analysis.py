"""Static-analysis suite tests (paddle_tpu/analysis/).

Each pass is exercised both ways: seeded-violation fixtures it MUST
flag, and known-good idioms it must NOT flag (the false-positive
exemptions — `is None`, membership tests, `.shape` metadata,
`jax.process_count()` — are contracts too).  The self-lint test runs
the whole suite over the real tree and must come back clean modulo the
committed baseline — that's the machine-checked version of PR 2's
one-sync-per-step comment.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.analysis import registered_surfaces
from paddle_tpu.analysis.runner import (run_passes, make_context,
                                        load_baseline, write_baseline,
                                        split_new, REPO_ROOT,
                                        DEFAULT_BASELINE)

pytestmark = pytest.mark.lint

AST_PASSES = ["tracer-safety", "host-sync", "collective-order"]


def _lint(tmp_path, code, passes=AST_PASSES, name="fixture.py"):
    (tmp_path / name).write_text(textwrap.dedent(code))
    return run_passes(paths=[str(tmp_path)], passes=passes)


def _codes(findings):
    return [f.code for f in findings]


class TestTracerSafety:
    def test_flags_every_seeded_violation(self, tmp_path):
        found = _lint(tmp_path, """
            import numpy as np
            import jax.numpy as jnp
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def bad_step(grads, lr):
                total = jnp.sum(grads)
                if total > 0:
                    lr = lr * 0.5
                while total > 1:
                    total = total - 1
                f = float(total)
                h = np.asarray(total)
                i = total.item()
                return f, h, i, len(grads)
            """, passes=["tracer-safety"])
        codes = _codes(found)
        assert codes.count("control-flow-on-traced") == 2  # if + while
        assert "cast-on-traced" in codes
        assert "numpy-on-traced" in codes
        assert "host-readback" in codes
        assert "len-on-traced" in codes

    def test_reaches_helpers_and_nested_defs(self, tmp_path):
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.analysis import jit_surface

            def helper(x):
                return x.item()            # reached via surface call

            @jit_surface
            def build():
                def step(xs):              # nested def = traced body
                    if xs:
                        return helper(xs)
                    return xs
                return jax.jit(step)

            def unreachable(x):
                return x.item()            # never flagged: not reachable
            """, passes=["tracer-safety"])
        quals = {(f.qualname, f.code) for f in found}
        assert ("helper", "host-readback") in quals
        assert ("build.step", "control-flow-on-traced") in quals
        assert not any(q.startswith("unreachable") for q, _ in quals)

    def test_known_good_idioms_stay_quiet(self, tmp_path):
        # amp/cache are closure config of the builder (the real stepper
        # shape); xs are the traced values
        found = _lint(tmp_path, """
            import jax.numpy as jnp
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def build(amp, cache):
                def good_step(xs):
                    out = []
                    for i, x in enumerate(xs):
                        if x is None:                    # identity: static
                            continue
                        if i in cache:                   # membership: keys
                            continue
                        if amp in ("O1", "O2") and \\
                                jnp.issubdtype(x.dtype, jnp.floating):
                            x = x.astype(jnp.bfloat16)   # metadata: static
                        n = x.shape[0]                   # shape: static
                        out.append(jnp.where(x > 0, x, n))
                    k = float(3.5)                       # host literal
                    return out, k
                return good_step
            """, passes=["tracer-safety"])
        assert found == []

    def test_ifexp_and_assert_on_traced_flagged(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(x, eos):
                a = 1 if x > 0 else 0            # traced: flag
                assert x > 0                     # traced: flag
                b = jnp.zeros(3) if eos is None else x   # static: quiet
                return a, b
            """, passes=["tracer-safety"])
        kinds = sorted(f.detail.split(":")[0] for f in found)
        assert kinds == ["assert", "if-expression"], found

    def test_membership_traced_array_vs_container_keys(self, tmp_path):
        # `k in dict_of_traced` probes static keys (quiet); `k in xs`
        # on a traced array calls the tracer's __contains__ (flagged)
        found = _lint(tmp_path, """
            import jax.numpy as jnp
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(xs, idx):
                table = dict(zip(idx, xs))
                hit = 0
                if 3 in table:
                    hit = 1
                if 3 in xs:
                    hit = 2
                return hit
            """, passes=["tracer-safety"])
        assert _codes(found) == ["control-flow-on-traced"]
        assert "3 in xs" in found[0].message

    def test_pragma_suppresses(self, tmp_path):
        found = _lint(tmp_path, """
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def step(xs):
                return len(xs)  # lint: allow(len-on-traced)
            """, passes=["tracer-safety"])
        assert found == []


class TestHostSync:
    def test_sync_inside_jit_surface_always_flagged(self, tmp_path):
        found = _lint(tmp_path, """
            import numpy as np
            from paddle_tpu.analysis import jit_surface
            from paddle_tpu.framework.guardian import _host_bool

            @jit_surface
            def step(flag):
                return _host_bool(flag), np.asarray(flag), flag.item()
            """, passes=["host-sync"])
        assert _codes(found) == ["sync-in-jit-surface"] * 3

    def test_monitored_module_budget(self, tmp_path):
        # a file at the monitored relpath is held to the allowlist:
        # grads_ok's budget is 1 `_host_bool`; a second one must fail,
        # and an un-allowlisted function gets no budget at all
        mod = tmp_path / "paddle_tpu" / "framework"
        mod.mkdir(parents=True)
        (mod / "guardian.py").write_text(textwrap.dedent("""
            def _host_bool(x):
                return bool(x)

            class NumericSentinel:
                def grads_ok(self, named, step):
                    ok = _host_bool(named)        # within budget
                    ok2 = _host_bool(named)       # budget exceeded
                    return ok and ok2

            def sneaky_new_path(flag):
                return _host_bool(flag)           # unbudgeted
            """))
        found = run_passes(paths=[str(tmp_path)], passes=["host-sync"])
        by_qual = {f.qualname: f.code for f in found}
        assert by_qual == {
            "NumericSentinel.grads_ok": "unbudgeted-host-sync",
            "sneaky_new_path": "unbudgeted-host-sync"}

    def test_pragma_does_not_consume_budget_slot(self, tmp_path):
        # a pragma'd new site must be exempt BEFORE budgeting, so the
        # pre-existing allowlisted site keeps its slot and the run
        # stays green (the remediation the error message suggests)
        mod = tmp_path / "paddle_tpu" / "framework"
        mod.mkdir(parents=True)
        (mod / "guardian.py").write_text(textwrap.dedent("""
            def _host_bool(x):
                return bool(x)

            class NumericSentinel:
                def grads_ok(self, named, step):
                    dbg = _host_bool(named)  # lint: allow(host-sync)
                    return _host_bool(named)     # the budgeted site
            """))
        found = run_passes(paths=[str(tmp_path)], passes=["host-sync"])
        assert found == [], [repr(f) for f in found]

    def test_extra_nested_surfaces_are_monitored(self, tmp_path):
        # EXTRA_JIT_SURFACES (decorator-unreachable nested defs) must be
        # held to the same no-sync rule — a suffix-matching fixture
        # stands in for paddle_tpu/models/generation.py
        mod = tmp_path / "paddle_tpu" / "models"
        mod.mkdir(parents=True)
        (mod / "generation.py").write_text(textwrap.dedent("""
            def generate(model, ids):
                def run(pv, prompt, key):
                    prompt.block_until_ready()     # sync in jit surface
                    return pv, prompt.item()
                return run
            """))
        found = run_passes(paths=[str(tmp_path)],
                           passes=["host-sync", "tracer-safety"])
        got = {(f.pass_name, f.code) for f in found}
        assert ("host-sync", "sync-in-jit-surface") in got
        assert ("tracer-safety", "host-readback") in got

    def test_renamed_extra_surface_is_a_finding(self, tmp_path):
        # a renamed nested def must not silently drop lint coverage:
        # a file matching an EXTRA_JIT_SURFACES relpath without the
        # registered qualname is itself flagged
        mod = tmp_path / "paddle_tpu" / "models"
        mod.mkdir(parents=True)
        (mod / "generation.py").write_text(textwrap.dedent("""
            def generate(model, ids):
                def sample_run(pv):        # renamed from `run`
                    return pv
                return sample_run
            """))
        found = run_passes(paths=[str(tmp_path)], passes=["tracer-safety"])
        assert {f.code for f in found} == {"unresolved-surface"}
        assert "generate.run" in {f.detail for f in found}

    def test_explicit_repo_paths_keep_policy_relpaths(self):
        # running over a subdirectory of the repo must not re-root
        # relpaths (which would silently disable monitored-module
        # matching, EXTRA surfaces, and baseline keys)
        ctx = make_context(paths=[os.path.join(REPO_ROOT, "paddle_tpu")])
        assert ctx.root == REPO_ROOT
        assert "paddle_tpu/framework/guardian.py" in ctx.index.by_relpath

    def test_real_hot_paths_fit_their_budgets(self):
        found = run_passes(passes=["host-sync"])
        baseline = load_baseline(os.path.join(REPO_ROOT, DEFAULT_BASELINE))
        new, _ = split_new(found, baseline)
        assert new == [], [repr(f) for f in new]


class TestCollectiveOrder:
    def test_rank_conditional_collective_flagged(self, tmp_path):
        found = _lint(tmp_path, """
            from paddle_tpu.distributed.collective import barrier

            def save(rank):
                if rank == 0:
                    barrier()

            def save2():
                from paddle_tpu.distributed import get_rank
                if get_rank() == 0:
                    barrier()
            """, passes=["collective-order"])
        assert _codes(found) == ["rank-conditional-collective"] * 2

    def test_divergent_order_flagged_same_order_not(self, tmp_path):
        found = _lint(tmp_path, """
            from paddle_tpu.distributed.collective import (barrier,
                                                           all_reduce)

            def bad(flag, x):
                if flag:
                    all_reduce(x)
                    barrier()
                else:
                    barrier()
                    all_reduce(x)

            def fine(flag, x):
                if flag:
                    all_reduce(x)
                    barrier()
                else:
                    all_reduce(x)
                    barrier()
            """, passes=["collective-order"])
        assert len(found) == 1
        assert found[0].code == "divergent-collective-order"
        assert found[0].qualname == "bad"

    def test_divergent_neutral_elif_chain_flagged_once(self, tmp_path):
        found = _lint(tmp_path, """
            from paddle_tpu.distributed.collective import (barrier,
                                                           all_reduce)

            def bad(mode, x):
                if mode == "a":
                    all_reduce(x)
                    barrier()
                elif mode == "b":
                    barrier()
                    all_reduce(x)
            """, passes=["collective-order"])
        assert _codes(found) == ["divergent-collective-order"]

    def test_nested_rank_branches_report_call_once(self, tmp_path):
        found = _lint(tmp_path, """
            from paddle_tpu.distributed.collective import barrier

            def bad(rank, local_rank):
                if rank == 0:
                    if local_rank == 0:
                        barrier()
            """, passes=["collective-order"])
        assert _codes(found) == ["rank-conditional-collective"]

    def test_elif_arms_report_once_with_their_own_condition(self,
                                                            tmp_path):
        found = _lint(tmp_path, """
            from paddle_tpu.distributed.collective import (barrier,
                                                           all_reduce)

            def chain(rank, x):
                if rank == 0:
                    barrier()
                elif rank == 1:
                    all_reduce(x)
            """, passes=["collective-order"])
        # one finding per call site, each under ITS arm's condition
        assert len(found) == 2
        by_detail = {f.detail: f for f in found}
        assert "barrier:rank == 0" in by_detail
        assert "all_reduce:rank == 1" in by_detail

    def test_uniform_conditions_stay_quiet(self, tmp_path):
        found = _lint(tmp_path, """
            import jax
            from paddle_tpu.distributed.collective import barrier

            def sync():
                if jax.process_count() > 1:      # uniform across ranks
                    barrier()
            """, passes=["collective-order"])
        assert found == []

    def test_data_dependent_collective_flagged(self, tmp_path):
        found = _lint(tmp_path, """
            import jax.numpy as jnp
            from paddle_tpu.distributed.collective import all_reduce

            def maybe(x):
                if jnp.any(x > 0):
                    all_reduce(x)
            """, passes=["collective-order"])
        assert _codes(found) == ["data-conditional-collective"]


class TestRegistryLints:
    # the orphan names are assembled at runtime: this test FILE is
    # itself scanned by the registry lints, and a literal orphan here
    # would (correctly!) fail the self-lint
    ORPHAN_FP = "store." + "no_such_site"
    ORPHAN_EVENT = "bogus" + "_event"

    def test_orphan_failpoint_flagged_registered_not(self, tmp_path):
        # built by concatenation so THIS file contains neither a
        # spec-shaped orphan literal nor a scannable set_failpoint call
        fixture = (
            'set_' + f'failpoint("guardian.poison_batch", "skip")\n'
            'set_' + f'failpoint("{self.ORPHAN_FP}", "raise")\n')
        (tmp_path / "t.py").write_text(fixture)
        found = run_passes(paths=[str(tmp_path)],
                           passes=["failpoint-refs"])
        assert [(f.code, f.detail) for f in found] == \
            [("orphan-failpoint", self.ORPHAN_FP)]

    def test_unknown_guardian_event_flagged(self, tmp_path):
        (tmp_path / "t.py").write_text(textwrap.dedent("""
            events("rollback")          # real event
            events("ORPHAN")            # drifted
            """).replace("ORPHAN", self.ORPHAN_EVENT))
        found = run_passes(paths=[str(tmp_path)], passes=["guardian-log"])
        assert [(f.code, f.detail) for f in found] == \
            [("unknown-guardian-event", self.ORPHAN_EVENT)]


    def test_doc_table_checked_on_explicit_docs_run(self, monkeypatch):
        # an explicit `docs/` run must still check the schema table —
        # simulate drift by adding an (undocumented) event to the
        # emitter schema
        from paddle_tpu.framework.guardian import EVENT_SCHEMA
        monkeypatch.setitem(EVENT_SCHEMA, "zz_drifted", {"step"})
        found = run_passes(paths=[os.path.join(REPO_ROOT, "docs")],
                           passes=["guardian-log"])
        assert [(f.code, f.detail) for f in found] == \
            [("schema-drift", "zz_drifted")]


class TestRunnerAndBaseline:
    def test_self_lint_clean_modulo_baseline(self):
        """THE gate: all passes over the real tree, no new findings."""
        findings = run_passes()
        baseline = load_baseline(os.path.join(REPO_ROOT, DEFAULT_BASELINE))
        new, _ = split_new(findings, baseline)
        assert new == [], "new lint findings:\n" + \
            "\n".join(repr(f) for f in new)

    def test_deterministic_ordering(self, tmp_path):
        code = """
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def s(a, b):
                x = a.item()
                y = b.item()
                return x, y, float(a)
            """
        keys1 = [f.key() for f in _lint(tmp_path, code)]
        keys2 = [f.key() for f in _lint(tmp_path, code)]
        # 2x host-readback + 1x cast (tracer) + 2x sync-in-jit-surface
        assert keys1 == keys2 and len(keys1) == 5

    def test_baseline_roundtrip_suppresses_old_not_new(self, tmp_path):
        (tmp_path / "f.py").write_text(textwrap.dedent("""
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def s(a):
                return a.item()
            """))
        found = run_passes(paths=[str(tmp_path)], passes=AST_PASSES)
        assert len(found) == 2      # tracer host-readback + host-sync
        bl_path = tmp_path / "baseline.json"
        write_baseline(str(bl_path), found)
        baseline = load_baseline(str(bl_path))
        new, old = split_new(
            run_passes(paths=[str(tmp_path)], passes=AST_PASSES), baseline)
        assert new == [] and len(old) == 2
        # a NEW violation in the same file is not absorbed by the key
        # of the old one
        (tmp_path / "f.py").write_text(textwrap.dedent("""
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def s(a):
                return a.item(), float(a)
            """))
        new, old = split_new(
            run_passes(paths=[str(tmp_path)], passes=AST_PASSES), baseline)
        assert [f.code for f in new] == ["cast-on-traced"]
        assert len(old) == 2

    def test_cli_full_tree_exits_zero(self):
        """Acceptance: `python -m paddle_tpu.analysis` runs all passes
        over the tree against the committed baseline and exits 0."""
        env = dict(os.environ, PYTHONPATH=REPO_ROOT,
                   JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis"],
            capture_output=True, text=True, env=env, cwd=REPO_ROOT,
            timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "OK: no new findings" in r.stdout

    def test_cli_seeded_violation_exits_one_and_json(self, tmp_path):
        (tmp_path / "bad.py").write_text(textwrap.dedent("""
            from paddle_tpu.analysis import jit_surface

            @jit_surface
            def s(a):
                if a > 0:
                    return a.item()
            """))
        env = dict(os.environ, PYTHONPATH=REPO_ROOT,
                   JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", str(tmp_path),
             "--no-baseline", "--json"],
            capture_output=True, text=True, env=env, cwd=REPO_ROOT,
            timeout=120)
        assert r.returncode == 1, r.stdout + r.stderr
        out = json.loads(r.stdout)
        assert out["new"] == out["total"] >= 3
        codes = {f["code"] for f in out["findings"]}
        assert {"control-flow-on-traced", "host-readback",
                "sync-in-jit-surface"} <= codes


class TestPathValidation:
    def test_nonexistent_path_is_an_error_not_a_green_run(self):
        from paddle_tpu.analysis import main as cli_main
        assert cli_main(["definitely/not/a/path.py"]) == 2

    def test_empty_match_is_an_error(self, tmp_path):
        d = tmp_path / "empty"
        d.mkdir()
        with pytest.raises(ValueError, match="no .py"):
            make_context(paths=[str(d)])

    def test_update_baseline_rejects_partial_scopes(self):
        # neither a path subset nor a pass subset may overwrite the
        # shared baseline — it would erase findings outside its scope
        from paddle_tpu.analysis import main as cli_main
        assert cli_main(["paddle_tpu/framework", "--update-baseline"]) == 2
        assert cli_main(["--passes", "host-sync",
                         "--update-baseline"]) == 2


class TestSurfaceRegistry:
    def test_runtime_registry_matches_annotations(self):
        import paddle_tpu.hapi.model          # noqa: F401
        import paddle_tpu.optimizer.optimizer  # noqa: F401
        import paddle_tpu.framework.guardian   # noqa: F401
        import paddle_tpu.models.generation    # noqa: F401
        regs = set(registered_surfaces())
        expect = {
            ("paddle_tpu.hapi.model", "_CompiledStepper._build_train"),
            ("paddle_tpu.hapi.model", "_CompiledStepper._build_grad"),
            ("paddle_tpu.hapi.model", "_CompiledStepper._build_apply"),
            ("paddle_tpu.hapi.model", "_CompiledStepper._build_eval"),
            ("paddle_tpu.optimizer.optimizer",
             "apply_functional_with_clip"),
            ("paddle_tpu.optimizer.optimizer",
             "Optimizer.apply_functional"),
            ("paddle_tpu.framework.guardian", "tree_all_finite"),
            ("paddle_tpu.models.generation", "generate.run"),
            ("paddle_tpu.models.generation", "generate.beam_run"),
        }
        assert expect <= regs, expect - regs

    def test_runtime_registry_mirrored_in_ast_sources(self):
        """Drift guard: every runtime-registered surface must be visible
        to the AST passes — either decorated in source, or (nested defs)
        mirrored in EXTRA_JIT_SURFACES.  A register_jit_surface() call
        without its mirror would silently drop the surface from
        analysis."""
        import paddle_tpu.hapi.model          # noqa: F401
        import paddle_tpu.optimizer.optimizer  # noqa: F401
        import paddle_tpu.framework.guardian   # noqa: F401
        import paddle_tpu.models.generation    # noqa: F401
        from paddle_tpu.analysis.allowlist import EXTRA_JIT_SURFACES
        extra = set(EXTRA_JIT_SURFACES)
        ctx = make_context()
        for module, qual in registered_surfaces():
            rel = module.replace(".", "/")
            mod = ctx.index.by_relpath.get(rel + ".py") or \
                ctx.index.by_relpath.get(rel + "/__init__.py")
            assert mod is not None, module
            fi = mod.funcs.get(qual)
            assert fi is not None, (module, qual)
            if not fi.is_surface:
                assert (mod.relpath, qual) in extra, (
                    f"{module}:{qual} is register_jit_surface()'d but "
                    "not mirrored in EXTRA_JIT_SURFACES — the AST "
                    "passes will never analyze it")

    def test_extra_surfaces_resolve_in_ast(self):
        """EXTRA_JIT_SURFACES entries must name functions that actually
        exist — a renamed nested def must not silently un-register."""
        from paddle_tpu.analysis.allowlist import EXTRA_JIT_SURFACES
        ctx = make_context()
        for rel, qual in EXTRA_JIT_SURFACES:
            mod = ctx.index.by_relpath.get(rel)
            assert mod is not None, rel
            assert qual in mod.funcs, (rel, qual)

    def test_allowlist_entries_point_at_real_functions(self):
        from paddle_tpu.analysis.allowlist import HOST_SYNC_ALLOWLIST
        ctx = make_context()
        for rel, qual, _callee in HOST_SYNC_ALLOWLIST:
            mod = ctx.index.by_relpath.get(rel)
            assert mod is not None, rel
            assert qual in mod.funcs, (rel, qual)
