"""TCPStore edge cases the retry/backoff work makes reachable: server
death mid-WAIT, ADD on non-integer bytes, reconnect across a server
restart (ISSUE 1 satellite).

All tests force the pure-Python client (``use_native=False``) — the
retry/reconnect machinery under test lives there; the native C++ client
keeps its own behavior.
"""
import struct
import threading
import time

import pytest

from paddle_tpu.distributed.store import TCPStore, _PyStoreServer


def _master():
    return TCPStore("127.0.0.1", 0, is_master=True, use_native=False)


class TestWaitEdges:
    def test_wait_expiry_is_clear_timeout(self):
        store = _master()
        try:
            t0 = time.monotonic()
            with pytest.raises(TimeoutError, match="expired"):
                store.wait("never-set", timeout=0.3)
            assert time.monotonic() - t0 < 5.0
        finally:
            store.close()

    def test_server_stopped_mid_wait(self):
        master = _master()
        client = TCPStore(master.host, master.port, use_native=False,
                          timeout=2.0)
        errs = []
        done = threading.Event()

        def waiter():
            try:
                client.wait("never-set", timeout=3.0)
            except (TimeoutError, ConnectionError) as e:
                errs.append(e)
            done.set()

        threading.Thread(target=waiter, daemon=True).start()
        time.sleep(0.3)             # let the WAIT park server-side
        master.close()              # server dies under the parked WAIT
        # the client must surface a clear error within its retry budget
        # (store timeout 2s + op timeout 3s): either the server's parting
        # status byte (TimeoutError) or reconnect exhaustion
        assert done.wait(10.0), "client hung after server death mid-WAIT"
        assert len(errs) == 1
        client.close()


class TestAddEdges:
    def test_add_on_non_integer_value_starts_from_zero(self):
        store = _master()
        try:
            store.set("k", b"not-an-int64")     # len != 8: counter resets
            assert store.add("k", 5) == 5
            # and the key now holds a proper little-endian int64
            assert struct.unpack("<q", store.get("k"))[0] == 5
            assert store.add("k", 2) == 7
        finally:
            store.close()

    def test_add_on_eight_stray_bytes_reinterprets(self):
        store = _master()
        try:
            store.set("k", struct.pack("<q", 40))
            assert store.add("k", 2) == 42      # SET then ADD interoperate
        finally:
            store.close()


class TestReconnect:
    def test_client_survives_server_restart(self):
        srv1 = _PyStoreServer(0)
        port = srv1.port
        client = TCPStore("127.0.0.1", port, use_native=False, timeout=10.0)
        client.set("before", b"1")
        srv1.stop()
        # restart on the SAME port (new empty KV — a real master restart)
        srv2 = None
        deadline = time.monotonic() + 5.0
        while srv2 is None:
            try:
                srv2 = _PyStoreServer(port)
            except OSError:         # TIME_WAIT straggler
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        try:
            client.set("after", b"2")           # reconnects under the hood
            assert client.get("after") == b"2"
            with pytest.raises(KeyError):
                client.get("before", timeout=0.2)   # state did NOT survive
        finally:
            client.close()
            srv2.stop()

    def test_ops_fail_cleanly_while_server_down(self):
        srv = _PyStoreServer(0)
        client = TCPStore("127.0.0.1", srv.port, use_native=False,
                          timeout=0.5)
        srv.stop()
        with pytest.raises((ConnectionError, TimeoutError)):
            client.set("k", b"v")
        client.close()
