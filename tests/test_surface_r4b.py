"""Round-4b surface additions: top-level tensor ops.

Golden values via numpy/scipy (reference: python/paddle/tensor/{math,
manipulation,logic}.py op semantics).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_shape_rank_tolist():
    x = paddle.to_tensor(np.zeros((2, 3, 4), np.float32))
    np.testing.assert_array_equal(paddle.shape(x).numpy(), [2, 3, 4])
    assert paddle.shape(x).dtype == paddle.int32
    assert int(paddle.rank(x)) == 3
    assert paddle.tolist(paddle.to_tensor([[1, 2], [3, 4]])) == [[1, 2], [3, 4]]


def test_stacks_match_numpy():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = a + 10
    ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
    np.testing.assert_allclose(paddle.hstack([ta, tb]).numpy(),
                               np.hstack([a, b]))
    np.testing.assert_allclose(paddle.vstack([ta, tb]).numpy(),
                               np.vstack([a, b]))
    np.testing.assert_allclose(paddle.dstack([ta, tb]).numpy(),
                               np.dstack([a, b]))


def test_unflatten_and_grad():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32), stop_gradient=False)
    y = paddle.unflatten(x * 3.0, 0, [3, -1] if False else [3, 4])
    assert y.shape == [3, 4]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full(12, 3.0))


def test_strided_slice():
    x = np.arange(60).reshape(3, 4, 5).astype(np.float32)
    t = paddle.to_tensor(x)
    got = paddle.strided_slice(t, axes=[1, 2], starts=[3, 4],
                               ends=[0, 0], strides=[-1, -2]).numpy()
    np.testing.assert_array_equal(got, x[:, 3:0:-1, 4:0:-2])
    got2 = paddle.strided_slice(t, axes=[0], starts=[0], ends=[3],
                                strides=[2]).numpy()
    np.testing.assert_array_equal(got2, x[::2])


def test_bessel_exp_scaled_and_sinc():
    from scipy import special
    v = np.linspace(0.1, 5.0, 7).astype(np.float32)
    t = paddle.to_tensor(v)
    np.testing.assert_allclose(paddle.i0e(t).numpy(), special.i0e(v),
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.i1e(t).numpy(), special.i1e(v),
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.sinc(t).numpy(), np.sinc(v), rtol=1e-5)


def test_fmod_c_semantics():
    x = np.array([-7.0, 7.0, -5.5], np.float32)
    y = np.array([3.0, -3.0, 2.0], np.float32)
    got = paddle.fmod(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    np.testing.assert_allclose(got, np.fmod(x, y))


def test_isposinf_isneginf():
    v = np.array([np.inf, -np.inf, np.nan, 1.0], np.float32)
    t = paddle.to_tensor(v)
    np.testing.assert_array_equal(paddle.isposinf(t).numpy(),
                                  np.isposinf(v))
    np.testing.assert_array_equal(paddle.isneginf(t).numpy(),
                                  np.isneginf(v))


def test_vecdot_batched():
    a = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    b = np.random.RandomState(1).randn(4, 3).astype(np.float32)
    got = paddle.linalg.vecdot(paddle.to_tensor(a),
                               paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(got, (a * b).sum(-1), rtol=1e-5)


def test_dtype_predicates():
    assert paddle.is_floating_point(paddle.to_tensor([1.0]))
    assert not paddle.is_floating_point(paddle.to_tensor([1]))
    assert paddle.is_integer(paddle.to_tensor([1]))
    assert not paddle.is_complex(paddle.to_tensor([1.0]))
    assert paddle.is_complex(paddle.to_tensor(np.array([1 + 2j],
                                                       np.complex64)))


def test_negative_alias():
    x = paddle.to_tensor([1.0, -2.0])
    np.testing.assert_allclose(paddle.negative(x).numpy(), [-1.0, 2.0])
    np.testing.assert_allclose(x.negative().numpy(), [-1.0, 2.0])


# -- nn additions -----------------------------------------------------------

def _hsigmoid_ref(x, lab, w, b, C):
    out = np.zeros((len(lab), 1))
    for i, l in enumerate(lab):
        node = l + C - 1
        tot = 0.0
        while node > 0:
            parent = (node - 1) // 2
            bit = 1.0 if node == 2 * parent + 2 else 0.0
            s = w[parent] @ x[i] + b[parent, 0]
            tot += max(s, 0) - s * bit + np.log1p(np.exp(-abs(s)))
            node = parent
        out[i, 0] = tot
    return out


def test_hsigmoid_loss_matches_tree_walk():
    rs = np.random.RandomState(0)
    N, F_, C = 5, 8, 6
    x = rs.randn(N, F_).astype(np.float32)
    lab = rs.randint(0, C, (N,))
    w = rs.randn(C - 1, F_).astype(np.float32) * 0.1
    b = rs.randn(C - 1, 1).astype(np.float32) * 0.1
    out = paddle.nn.functional.hsigmoid_loss(
        paddle.to_tensor(x), paddle.to_tensor(lab), C,
        paddle.to_tensor(w), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), _hsigmoid_ref(x, lab, w, b, C),
                               rtol=1e-4)


def test_hsigmoid_loss_grad_and_layer():
    rs = np.random.RandomState(1)
    x = paddle.to_tensor(rs.randn(4, 8).astype(np.float32),
                         stop_gradient=False)
    lab = paddle.to_tensor(rs.randint(0, 10, (4,)))
    layer = paddle.nn.HSigmoidLoss(8, 10)
    loss = layer(x, lab).sum()
    loss.backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()
    assert layer.weight.grad is not None


def test_hsigmoid_custom_path():
    # two-class custom tree: single internal node, code 0/1
    x = np.array([[1.0, -1.0]], np.float32)
    w = np.array([[0.5, 0.5]], np.float32)
    table = np.array([[0]], np.int64)
    code = np.array([[1]], np.int64)
    out = paddle.nn.functional.hsigmoid_loss(
        paddle.to_tensor(x), paddle.to_tensor([0]), 2,
        paddle.to_tensor(w), path_table=paddle.to_tensor(table),
        path_code=paddle.to_tensor(code))
    s = 0.0  # w.x = 0
    want = max(s, 0) - s * 1 + np.log1p(np.exp(-abs(s)))
    np.testing.assert_allclose(out.numpy(), [[want]], rtol=1e-5)


def test_class_center_sample():
    lab = paddle.to_tensor(np.array([1, 3, 3, 9]))
    remap, centers = paddle.nn.functional.class_center_sample(lab, 20, 6)
    c = centers.numpy()
    assert len(c) == 6 and len(set(c.tolist())) == 6
    assert {1, 3, 9} <= set(c.tolist())
    np.testing.assert_array_equal(c[remap.numpy()], [1, 3, 3, 9])


def test_class_center_sample_all_positive():
    lab = paddle.to_tensor(np.arange(8))
    remap, centers = paddle.nn.functional.class_center_sample(lab, 8, 4)
    np.testing.assert_array_equal(np.sort(centers.numpy()), np.arange(8))


def test_pixel_unshuffle_layer():
    x = np.random.RandomState(0).randn(1, 4, 8, 8).astype(np.float32)
    y = paddle.nn.PixelUnshuffle(2)(paddle.to_tensor(x))
    assert y.shape == [1, 16, 4, 4]
    back = paddle.nn.PixelShuffle(2)(y)
    np.testing.assert_allclose(back.numpy(), x)


def test_multi_margin_loss_layer():
    rs = np.random.RandomState(0)
    x = rs.randn(4, 5).astype(np.float32)
    lab = rs.randint(0, 5, (4,))
    got = paddle.nn.MultiMarginLoss()(paddle.to_tensor(x),
                                      paddle.to_tensor(lab))
    want = paddle.nn.functional.multi_margin_loss(
        paddle.to_tensor(x), paddle.to_tensor(lab))
    np.testing.assert_allclose(float(got), float(want))


# -- weight-only / llm.int8 quant ------------------------------------------

def test_weight_quantize_roundtrip():
    rs = np.random.RandomState(0)
    w = rs.randn(16, 8).astype(np.float32)
    q, s = paddle.nn.quant.weight_quantize(paddle.to_tensor(w))
    assert q.numpy().dtype == np.int8 and s.shape == [8]
    wd = paddle.nn.quant.weight_dequantize(q, s)
    assert np.abs(wd.numpy() - w).max() < np.abs(w).max() / 100


def test_weight_only_linear():
    rs = np.random.RandomState(0)
    w = rs.randn(16, 8).astype(np.float32)
    x = rs.randn(4, 16).astype(np.float32)
    b = rs.randn(8).astype(np.float32)
    q, s = paddle.nn.quant.weight_quantize(paddle.to_tensor(w))
    y = paddle.nn.quant.weight_only_linear(
        paddle.to_tensor(x), q, bias=paddle.to_tensor(b), weight_scale=s)
    want = x @ (q.numpy().astype(np.float32) * s.numpy()) + b
    # default matmul precision (bf16 passes) -> loose tolerance
    np.testing.assert_allclose(y.numpy(), want, rtol=0.05, atol=0.05)


def test_llm_int8_linear_outlier_decomposition():
    rs = np.random.RandomState(0)
    w = rs.randn(16, 8).astype(np.float32)
    x = rs.randn(4, 16).astype(np.float32)
    x[:, 3] *= 20.0   # outlier column
    q, s = paddle.nn.quant.weight_quantize(paddle.to_tensor(w))
    y = paddle.nn.quant.llm_int8_linear(paddle.to_tensor(x), q,
                                        weight_scale=s, threshold=6.0)
    want = x @ (q.numpy().astype(np.float32) * s.numpy())
    np.testing.assert_allclose(y.numpy(), want, rtol=0.1, atol=0.2)


def test_nn_quant_stub_identity():
    x = paddle.to_tensor([1.0, 2.0])
    np.testing.assert_allclose(paddle.nn.quant.Stub()(x).numpy(), [1, 2])


# -- beam search decoding ---------------------------------------------------

class _ToyCell:
    """Deterministic 'cell': logits depend only on the input token via a
    fixed table; state counts steps."""

    def __init__(self, table):
        self.table = paddle.to_tensor(table)

    def __call__(self, inputs, states):
        import paddle_tpu as P
        logits = P.gather(self.table, inputs, axis=0)
        return logits, states


def _brute_force_beam(table, start, end, beam, steps):
    """Enumerate all token sequences, score like beam search (sum of
    log-softmax steps, sequences frozen at end token), return the best
    final beam score set."""
    from itertools import product
    V = table.shape[1]

    def logsoftmax(v):
        v = v - v.max()
        return v - np.log(np.exp(v).sum())

    best = []
    for seq in product(range(V), repeat=steps):
        score, cur, finished = 0.0, start, False
        valid = True
        for tok in seq:
            if finished:
                if tok != end:
                    valid = False
                    break
                continue
            score += logsoftmax(table[cur])[tok]
            cur = tok
            if tok == end:
                finished = True
        if valid:
            best.append((score, seq))
    best.sort(key=lambda t: -t[0])
    return best


def test_beam_search_matches_brute_force():
    rs = np.random.RandomState(7)
    V = 5
    table = rs.randn(V, V).astype(np.float32)
    end = V - 1
    cell = _ToyCell(table)
    dec = paddle.nn.BeamSearchDecoder(cell, start_token=0, end_token=end,
                                      beam_size=3)
    B = 2
    init_state = paddle.to_tensor(np.zeros((B, 4), np.float32))
    out, fstate = paddle.nn.dynamic_decode(dec, inits=[init_state],
                                           max_step_num=4)
    ids = out.numpy()            # (B, T, beam)
    assert ids.shape[0] == B and ids.shape[2] == 3
    scores = fstate.log_probs.numpy()      # (B, beam)
    brute = _brute_force_beam(table, 0, end, 3, ids.shape[1])
    # best beam score must equal the true best sequence score
    np.testing.assert_allclose(scores[0, 0], brute[0][0], rtol=1e-4)
    np.testing.assert_allclose(scores[1, 0], brute[0][0], rtol=1e-4)
    # and the decoded top beam must be that sequence
    np.testing.assert_array_equal(ids[0, :, 0], list(brute[0][1]))


def test_dynamic_decode_stops_on_finish():
    V = 4
    # token 'end'=3 gets overwhelming logit from any input -> finishes fast
    table = np.full((V, V), -5.0, np.float32)
    table[:, 3] = 5.0
    cell = _ToyCell(table)
    dec = paddle.nn.BeamSearchDecoder(cell, start_token=0, end_token=3,
                                      beam_size=2)
    init_state = paddle.to_tensor(np.zeros((1, 2), np.float32))
    out, fstate, lens = paddle.nn.dynamic_decode(
        dec, inits=[init_state], max_step_num=50, return_length=True)
    assert out.numpy().shape[1] < 50       # stopped early
    assert fstate.finished.numpy().all()
    assert (lens.numpy() >= 1).all()


# -- static additions -------------------------------------------------------

def test_static_save_load_roundtrip(tmp_path):
    import paddle_tpu.static as static
    import paddle_tpu.nn as nn
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            net = nn.Linear(4, 2)
            out = net(x)
        static.save(main, str(tmp_path / "model"))
        # perturb, then restore
        orig = net.weight.numpy().copy()
        net.weight.set_value(np.zeros_like(orig))
        static.load(main, str(tmp_path / "model"))
        np.testing.assert_allclose(net.weight.numpy(), orig)
    finally:
        paddle.disable_static()


def test_set_program_state(tmp_path):
    import paddle_tpu.static as static
    import paddle_tpu.nn as nn
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 3], "float32")
            net = nn.Linear(3, 3)
            net(x)
        params = static._program_parameters(main)
        state = {nm: np.full(np.asarray(t._value).shape, 2.5, np.float32)
                 for nm, t in params.items()}
        static.set_program_state(main, state)
        for t in params.values():
            np.testing.assert_allclose(np.asarray(t._value), 2.5)
    finally:
        paddle.disable_static()


def test_static_variable_alias_and_global_var():
    import paddle_tpu.static as static
    from paddle_tpu.framework.core import Tensor
    assert static.Variable is Tensor
    g = static.create_global_var([2], 3.0, "float32", name="gv_t")
    np.testing.assert_allclose(g.numpy(), [3.0, 3.0])
    assert "gv_t" in static.global_scope().vars


def test_static_accuracy_topk():
    import paddle_tpu.static as static
    pred = np.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]], np.float32)
    lab = np.array([[2], [0]])
    a1 = float(static.accuracy(paddle.to_tensor(pred),
                               paddle.to_tensor(lab), k=1))
    a2 = float(static.accuracy(paddle.to_tensor(pred),
                               paddle.to_tensor(lab), k=2))
    assert a1 == 0.5 and a2 == 1.0


def test_static_auc_rank_statistic():
    import paddle_tpu.static as static
    # scores 0.8,0.4,0.7 labels 1,0,1 -> perfect separation auc=1
    p = np.array([[0.2, 0.8], [0.6, 0.4], [0.3, 0.7]], np.float32)
    lab = np.array([1, 0, 1])
    a, _, _ = static.auc(paddle.to_tensor(p), paddle.to_tensor(lab))
    np.testing.assert_allclose(float(a), 1.0)
    # neg between the two pos: one of two pairs inverted -> auc=0.5
    p2 = np.array([[0.2, 0.8], [0.6, 0.5], [0.3, 0.1]], np.float32)
    a2, _, _ = static.auc(paddle.to_tensor(p2), paddle.to_tensor(lab))
    np.testing.assert_allclose(float(a2), 0.5)


# -- io additions -----------------------------------------------------------

def test_concat_dataset():
    a = paddle.io.TensorDataset([paddle.to_tensor(np.arange(3))])
    b = paddle.io.TensorDataset([paddle.to_tensor(np.arange(10, 12))])
    cd = paddle.io.ConcatDataset([a, b])
    assert len(cd) == 5
    assert int(cd[0][0]) == 0 and int(cd[3][0]) == 10
    assert int(cd[-1][0]) == 11


def test_subset_random_sampler():
    s = paddle.io.SubsetRandomSampler([1, 5, 9])
    got = sorted(iter(s))
    assert got == [1, 5, 9] and len(s) == 3


# -- distributed additions --------------------------------------------------

def test_distributed_is_available_and_state_dict_reexport():
    import paddle_tpu.distributed as dist
    assert dist.is_available() is True
    assert dist.save_state_dict is dist.checkpoint.save_state_dict
    assert dist.load_state_dict is dist.checkpoint.load_state_dict


def test_shard_layer_replicates_params():
    import jax
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    mesh = dist.ProcessMesh(np.arange(len(jax.devices())), dim_names=["dp"])
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    calls = []

    def shard_fn(name, sub, m):
        calls.append(name)
        for pname, p in list(sub._parameters.items()):
            if p is not None:
                sub._parameters[pname] = dist.shard_tensor(
                    p, m, [dist.Replicate()] * p.ndim)

    dist.shard_layer(net, mesh, shard_fn)
    assert calls                       # visited sublayers
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 4).astype(np.float32))
    y = net(x)
    assert y.shape == [2, 2]


# -- utils.download ---------------------------------------------------------

def test_download_offline_contract(tmp_path, monkeypatch):
    from paddle_tpu.utils import download
    with pytest.raises(RuntimeError, match="offline"):
        download.get_weights_path_from_url("http://host/w.pdparams")
    # pre-seeded file resolves
    f = tmp_path / "w.pdparams"
    f.write_bytes(b"x")
    got = download.get_path_from_url("http://host/w.pdparams",
                                     root_dir=str(tmp_path))
    assert got == str(f)


# -- vision io ops ----------------------------------------------------------

def test_read_file_decode_jpeg(tmp_path):
    from PIL import Image
    import io as _io
    yy, xx = np.mgrid[0:10, 0:12]
    img = np.stack([yy * 20, xx * 20, yy * 10 + xx * 10],
                   -1).astype(np.uint8)
    p = tmp_path / "t.jpg"
    Image.fromarray(img).save(str(p), format="JPEG", quality=95)
    raw = paddle.vision.ops.read_file(str(p))
    assert raw.numpy().dtype == np.uint8 and raw.numpy().ndim == 1
    dec = paddle.vision.ops.decode_jpeg(raw)
    assert dec.shape == [3, 10, 12] and dec.numpy().dtype == np.uint8
    # lossy codec: mean error small
    assert np.abs(dec.numpy().astype(int)
                  - img.transpose(2, 0, 1).astype(int)).mean() < 20
    gray = paddle.vision.ops.decode_jpeg(raw, mode="gray")
    assert gray.shape == [1, 10, 12]


# -- incubate graph sampling ------------------------------------------------

def _toy_csc():
    # 4 nodes; in-neighbors: 0<-{1,2}, 1<-{0,2,3}, 2<-{0}, 3<-{1}
    row = np.array([1, 2, 0, 2, 3, 0, 1])
    colptr = np.array([0, 2, 5, 6, 7])
    return row, colptr


def test_graph_sample_neighbors_full_and_capped():
    import paddle_tpu.incubate as inc
    row, colptr = _toy_csc()
    nb, ct = inc.graph_sample_neighbors(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([0, 1])))
    np.testing.assert_array_equal(ct.numpy(), [2, 3])
    np.testing.assert_array_equal(np.sort(nb.numpy()[:2]), [1, 2])
    np.testing.assert_array_equal(np.sort(nb.numpy()[2:]), [0, 2, 3])
    # capped at 2: per-node neighbor sets are subsets
    nb2, ct2 = inc.graph_sample_neighbors(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([1])), sample_size=2)
    assert ct2.numpy().tolist() == [2]
    assert set(nb2.numpy().tolist()) <= {0, 2, 3}


def test_graph_sample_neighbors_eids():
    import paddle_tpu.incubate as inc
    row, colptr = _toy_csc()
    eids = np.arange(100, 107)
    nb, ct, ei = inc.graph_sample_neighbors(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([2])), eids=paddle.to_tensor(eids),
        return_eids=True)
    np.testing.assert_array_equal(nb.numpy(), [0])
    np.testing.assert_array_equal(ei.numpy(), [105])


def test_graph_reindex():
    import paddle_tpu.incubate as inc
    src, dst, nodes = inc.graph_reindex(
        paddle.to_tensor(np.array([10, 20])),
        paddle.to_tensor(np.array([20, 30, 10, 40])),
        paddle.to_tensor(np.array([2, 2])))
    np.testing.assert_array_equal(nodes.numpy(), [10, 20, 30, 40])
    np.testing.assert_array_equal(src.numpy(), [1, 2, 0, 3])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1])
    # reconstruct original edges through out_nodes
    np.testing.assert_array_equal(nodes.numpy()[src.numpy()],
                                  [20, 30, 10, 40])


def test_graph_khop_sampler_edges_valid():
    import paddle_tpu.incubate as inc
    row, colptr = _toy_csc()
    es, ed, si, rn = inc.graph_khop_sampler(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([0])), [2, 2])
    nodes = si.numpy()
    # every reindexed edge maps back to a real CSC edge
    true_edges = set()
    for dst_node in range(4):
        for i in range(colptr[dst_node], colptr[dst_node + 1]):
            true_edges.add((row[i], dst_node))
    for s, d in zip(es.numpy(), ed.numpy()):
        assert (nodes[s], nodes[d]) in true_edges
    np.testing.assert_array_equal(nodes[rn.numpy()], [0])


# -- sparse attention -------------------------------------------------------

def _dense_attn_ref(q, k, v, mask):
    D = q.shape[-1]
    s = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
    s = np.where(mask, s, -1e9)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhts,bhsd->bhtd", p, v)


def test_sparse_attention_banded_matches_masked_dense():
    rs = np.random.RandomState(3)
    B, H, T, D = 2, 2, 6, 4
    q, k, v = [rs.randn(B, H, T, D).astype(np.float32) for _ in range(3)]
    # band: each row attends to {t-1, t}
    offs, cols, mask = [], [], np.zeros((T, T), bool)
    n = 0
    offs.append(0)
    for t in range(T):
        for c in ([t] if t == 0 else [t - 1, t]):
            cols.append(c)
            mask[t, c] = True
            n += 1
        offs.append(n)
    # ragged rows -> pad nnz arrays per (B,H) uniformly (same pattern)
    offset = np.tile(np.asarray(offs, np.int32)[None, None], (B, H, 1))
    columns = np.tile(np.asarray(cols, np.int32)[None, None], (B, H, 1))
    out = paddle.nn.functional.sparse_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(offset), paddle.to_tensor(columns))
    ref = _dense_attn_ref(q, k, v, mask[None, None])
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-2, atol=2e-2)


def test_sparse_attention_key_padding_mask():
    rs = np.random.RandomState(4)
    B, H, T, D = 1, 1, 4, 4
    q, k, v = [rs.randn(B, H, T, D).astype(np.float32) for _ in range(3)]
    offset = np.arange(0, (T + 1) * T, T, dtype=np.int32).reshape(1, 1, -1)
    cols = np.tile(np.arange(T, dtype=np.int32), T).reshape(1, 1, -1)
    kpm = np.array([[1, 1, 0, 0]], np.float32)   # last two keys padded
    out = paddle.nn.functional.sparse_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(offset), paddle.to_tensor(cols),
        key_padding_mask=paddle.to_tensor(kpm))
    mask = np.zeros((1, 1, T, T), bool)
    mask[..., :2] = True
    ref = _dense_attn_ref(q, k, v, mask)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-2, atol=2e-2)


def test_sparse_attention_grad_flows():
    rs = np.random.RandomState(5)
    B, H, T, D = 1, 1, 4, 4
    q = paddle.to_tensor(rs.randn(B, H, T, D).astype(np.float32),
                         stop_gradient=False)
    k, v = [paddle.to_tensor(rs.randn(B, H, T, D).astype(np.float32))
            for _ in range(2)]
    offset = paddle.to_tensor(
        np.arange(0, (T + 1) * T, T, dtype=np.int32).reshape(1, 1, -1))
    cols = paddle.to_tensor(
        np.tile(np.arange(T, dtype=np.int32), T).reshape(1, 1, -1))
    out = paddle.nn.functional.sparse_attention(q, k, v, offset, cols)
    out.sum().backward()
    assert q.grad is not None and np.isfinite(q.grad.numpy()).all()


# -- review-fix regressions -------------------------------------------------

def test_hsigmoid_label_column_shape():
    rs = np.random.RandomState(0)
    x = rs.randn(3, 4).astype(np.float32)
    w = rs.randn(5, 4).astype(np.float32)
    lab_flat = np.array([0, 2, 5])
    a = paddle.nn.functional.hsigmoid_loss(
        paddle.to_tensor(x), paddle.to_tensor(lab_flat), 6,
        paddle.to_tensor(w))
    b = paddle.nn.functional.hsigmoid_loss(
        paddle.to_tensor(x), paddle.to_tensor(lab_flat.reshape(-1, 1)), 6,
        paddle.to_tensor(w))
    np.testing.assert_allclose(a.numpy(), b.numpy())


def test_auc_ties_midrank():
    import paddle_tpu.static as static
    p = np.array([[0.5, 0.5], [0.5, 0.5]], np.float32)
    lab = np.array([0, 1])
    a, _, _ = static.auc(paddle.to_tensor(p), paddle.to_tensor(lab))
    np.testing.assert_allclose(float(a), 0.5)


def test_auc_pr_curve():
    import paddle_tpu.static as static
    # perfect ranking: PR AUC ~ 1
    p = np.array([[0.1, 0.9], [0.2, 0.8], [0.9, 0.1], [0.8, 0.2]],
                 np.float32)
    lab = np.array([1, 1, 0, 0])
    a, _, _ = static.auc(paddle.to_tensor(p), paddle.to_tensor(lab),
                         curve="PR")
    assert float(a) > 0.99
    with pytest.raises(ValueError):
        static.auc(paddle.to_tensor(p), paddle.to_tensor(lab), curve="XYZ")


def test_khop_no_duplicate_edges_on_cycle():
    import paddle_tpu.incubate as inc
    # 0 <-> 1 cycle in CSC
    row = np.array([1, 0])
    colptr = np.array([0, 1, 2])
    es, ed, si, rn = inc.graph_khop_sampler(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([0, 1])), [1, 1])
    edges = list(zip(es.numpy().tolist(), ed.numpy().tolist()))
    assert len(edges) == len(set(edges)) == 2


def test_concat_dataset_index_errors():
    a = paddle.io.TensorDataset([paddle.to_tensor(np.arange(3))])
    cd = paddle.io.ConcatDataset([a, a])
    with pytest.raises(IndexError):
        cd[6]
    with pytest.raises(IndexError):
        cd[-7]
    assert int(cd[-6][0]) == 0


def test_beam_search_nested_cell_states():
    # LSTM-style nested state [(h, c)] survives initialize/step
    rs = np.random.RandomState(2)
    V = 4
    table = rs.randn(V, V).astype(np.float32)

    class _NestCell:
        def __call__(self, inputs, states):
            logits = paddle.gather(paddle.to_tensor(table), inputs, axis=0)
            return logits, states

    dec = paddle.nn.BeamSearchDecoder(_NestCell(), start_token=0,
                                      end_token=V - 1, beam_size=2)
    h = paddle.to_tensor(np.zeros((1, 3), np.float32))
    c = paddle.to_tensor(np.zeros((1, 3), np.float32))
    out, fstate = paddle.nn.dynamic_decode(dec, inits=[(h, c)],
                                           max_step_num=3)
    assert out.numpy().shape[0] == 1


def test_io_star_export_includes_new_names():
    import paddle_tpu.io as pio
    assert "ConcatDataset" in pio.__all__
    assert "SubsetRandomSampler" in pio.__all__


def test_class_center_sample_group_seed_rank_invariant(monkeypatch):
    # with a group, the negative draw must depend only on the unioned
    # positives + global seed, not the per-rank key stream position
    import paddle_tpu.nn.functional as F

    class _FakeGroup:
        pass

    def fake_allgather(out, obj, group=None):
        out.extend([[1, 3], [3, 9]])

    import paddle_tpu.distributed.collective as coll
    monkeypatch.setattr(coll, "all_gather_object", fake_allgather)
    paddle.seed(123)
    _, c1 = F.class_center_sample(paddle.to_tensor(np.array([1, 3])), 20, 6,
                                  group=_FakeGroup())
    # advance the local key stream (simulates rank-divergent RNG use)
    paddle.rand([4])
    _, c2 = F.class_center_sample(paddle.to_tensor(np.array([1, 3])), 20, 6,
                                  group=_FakeGroup())
    np.testing.assert_array_equal(c1.numpy(), c2.numpy())


# -- tensor method batch ----------------------------------------------------

def test_inplace_method_family_r4b():
    x = paddle.to_tensor([2.0, 8.0])
    x.divide_(paddle.to_tensor(2.0))
    np.testing.assert_allclose(x.numpy(), [1.0, 4.0])
    y = paddle.to_tensor([-1.5, 2.5])
    y.abs_()
    np.testing.assert_allclose(y.numpy(), [1.5, 2.5])
    z = paddle.to_tensor([[1.0, 2.0]])
    z.squeeze_()
    assert z.shape == [2]
    m = paddle.to_tensor([1.0, 2.0])
    m.masked_fill_(paddle.to_tensor([True, False]), 9.0)
    np.testing.assert_allclose(m.numpy(), [9.0, 2.0])
    p = paddle.to_tensor([1.0, 2.0])
    p.pow_(paddle.to_tensor(2.0))
    np.testing.assert_allclose(p.numpy(), [1.0, 4.0])


def test_inplace_grad_flows_through_rebind():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    y.tanh_()
    y.sum().backward()
    want = 3.0 * (1.0 - np.tanh(np.array([3.0, 6.0])) ** 2)
    np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-4, atol=1e-6)


def test_copy_and_bernoulli_():
    z = paddle.to_tensor([1.0, 2.0])
    z.copy_(paddle.to_tensor([9.0, 8.0]))
    np.testing.assert_allclose(z.numpy(), [9.0, 8.0])
    b = paddle.to_tensor(np.zeros(2000, np.float32))
    b.bernoulli_(0.3)
    assert 0.2 < b.numpy().mean() < 0.4


def test_method_aliases_r4b():
    y = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(y.t().numpy(), [[1.0, 3.0], [2.0, 4.0]])
    assert y.ndimension() == 2 and int(y.rank()) == 2
    np.testing.assert_allclose(
        paddle.to_tensor([1.5, -1.5]).frac().numpy(), [0.5, -0.5])
    assert paddle.to_tensor([1.0, np.nan]).nanmean().numpy() == 1.0
    g = paddle.to_tensor([12, 18]).gcd(paddle.to_tensor([8, 12]))
    np.testing.assert_array_equal(g.numpy(), [4, 6])
    s = paddle.to_tensor(np.random.RandomState(0)
                         .rand(5).astype(np.float32))
    out = s.multinomial(3, replacement=True)
    assert out.shape == [3]


def test_static_amp_namespace():
    import paddle_tpu.static as static
    assert hasattr(static, "amp")
    with static.amp.auto_cast(False):
        pass
    ol = static.amp.CustomOpLists(custom_white_list=["matmul"])
    assert "matmul" in ol.white_list

    @static.amp.fp16_guard
    def f(x):
        return x + 1

    assert float(f(paddle.to_tensor(1.0))) == 2.0
