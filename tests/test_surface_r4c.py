"""Round-4c surface additions: printoptions, Bilinear init,
clip_grad_value_, saved_tensors_hooks, fused layers, sparse.mask_as."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_set_printoptions_roundtrip():
    t = paddle.to_tensor([1.23456789])
    paddle.set_printoptions(precision=2, sci_mode=True)
    try:
        r = repr(t)
        assert "e" in r.lower()
    finally:
        paddle.set_printoptions(precision=8, sci_mode=False)
    assert "1.2345679" in repr(t)


def test_bilinear_initializer_kernel():
    k = paddle.nn.initializer.Bilinear()((1, 1, 4, 4), "float32")
    got = np.asarray(k)[0, 0]
    # symmetric, separable bilinear weights for factor-2 upsampling
    want1d = np.array([0.25, 0.75, 0.75, 0.25])
    np.testing.assert_allclose(got, np.outer(want1d, want1d))
    np.testing.assert_allclose(got, got.T)


def test_bilinear_initializer_rejects_vector():
    with pytest.raises(ValueError):
        paddle.nn.initializer.Bilinear()((4,), "float32")


def test_clip_grad_value_():
    x = paddle.to_tensor([1.0, -2.0], stop_gradient=False)
    (x * paddle.to_tensor([10.0, 10.0])).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [10.0, 10.0])
    paddle.nn.utils.clip_grad_value_([x], 3.0)
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])


def test_saved_tensors_hooks_pack_unpack():
    from paddle_tpu.autograd import PyLayer, saved_tensors_hooks
    events = []

    def pack(t):
        events.append("pack")
        return t.numpy()

    def unpack(v):
        events.append("unpack")
        return paddle.to_tensor(v)

    class Sq(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * 2.0 * x

    x = paddle.to_tensor([3.0], stop_gradient=False)
    with saved_tensors_hooks(pack, unpack):
        y = Sq.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])
    assert events == ["pack", "unpack"]
    # outside the context the hooks are inactive
    events.clear()
    x2 = paddle.to_tensor([2.0], stop_gradient=False)
    Sq.apply(x2).backward()
    assert events == [] and x2.grad.numpy()[0] == 4.0


def test_fused_matmul_bias_transposes():
    F = paddle.incubate.nn.functional
    rs = np.random.RandomState(0)
    a = rs.randn(3, 4).astype(np.float32)
    w = rs.randn(5, 4).astype(np.float32)      # transposed weight
    b = rs.randn(5).astype(np.float32)
    out = F.fused_matmul_bias(paddle.to_tensor(a), paddle.to_tensor(w),
                              paddle.to_tensor(b), transpose_y=True)
    np.testing.assert_allclose(out.numpy(), a @ w.T + b, rtol=2e-2,
                               atol=2e-2)


def test_fused_dropout_add_layer():
    fda = paddle.incubate.nn.FusedDropoutAdd(p=0.5)
    fda.eval()
    out = fda(paddle.to_tensor([1.0, 2.0]), paddle.to_tensor([3.0, 4.0]))
    np.testing.assert_allclose(out.numpy(), [4.0, 6.0])
    fda.train()
    x = paddle.to_tensor(np.ones(1000, np.float32))
    y = paddle.to_tensor(np.zeros(1000, np.float32))
    o = fda(x, y).numpy()
    # upscale_in_train: surviving entries are 1/keep_prob
    assert set(np.round(np.unique(o), 3).tolist()) <= {0.0, 2.0}


def test_fused_ec_moe_routing_and_grad():
    rs = np.random.RandomState(1)
    moe = paddle.incubate.nn.FusedEcMoe(8, 16, 4)
    x = paddle.to_tensor(rs.randn(2, 6, 8).astype(np.float32),
                         stop_gradient=False)
    g = paddle.to_tensor(rs.randn(2, 6, 4).astype(np.float32))
    y = moe(x, g)
    assert y.shape == [2, 6, 8]
    y.sum().backward()
    assert np.isfinite(x.grad.numpy()).all()
    assert np.isfinite(moe.bmm0_weight.grad.numpy()).all()
    # one-expert, capacity==tokens degenerates to a dense FFN on all
    # tokens scaled by softmax prob 1.0
    moe1 = paddle.incubate.nn.FusedEcMoe(4, 8, 1)
    x1 = paddle.to_tensor(rs.randn(1, 5, 4).astype(np.float32))
    g1 = paddle.to_tensor(np.zeros((1, 5, 1), np.float32))
    out1 = moe1(x1, g1).numpy()
    w0 = moe1.bmm0_weight.numpy()[0]
    b0 = moe1.bmm0_bias.numpy()[0]
    w1 = moe1.bmm1_weight.numpy()[0]
    b1 = moe1.bmm1_bias.numpy()[0]
    xx = x1.numpy().reshape(5, 4)
    h = xx @ w0 + b0
    # jax.nn.gelu default = tanh approximation
    gelu = 0.5 * h * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (h + 0.044715 * h ** 3)))
    want = gelu @ w1 + b1
    np.testing.assert_allclose(out1.reshape(5, 4), want, rtol=5e-2,
                               atol=5e-2)


def test_sparse_mask_as():
    idx = paddle.to_tensor(np.array([[0, 1], [1, 0]]))
    m = paddle.sparse.sparse_coo_tensor(idx, paddle.to_tensor([1.0, 1.0]),
                                        [2, 2])
    x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    s = paddle.sparse.mask_as(x, m)
    np.testing.assert_allclose(s.values().numpy(), [2.0, 3.0])
    np.testing.assert_allclose(s.to_dense().numpy(),
                               [[0.0, 2.0], [3.0, 0.0]])
    m2 = paddle.sparse.sparse_csr_tensor([0, 1, 2], [1, 0],
                                         paddle.to_tensor([1.0, 1.0]),
                                         [2, 2])
    s2 = paddle.sparse.mask_as(x, m2)
    np.testing.assert_allclose(s2.values().numpy(), [2.0, 3.0])
    with pytest.raises(TypeError):
        paddle.sparse.mask_as(x, x)


# -- review-fix regressions (r4c review) ------------------------------------

def test_graph_reindex_duplicate_centers():
    import paddle_tpu.incubate as inc
    src, dst, nodes = inc.graph_reindex(
        paddle.to_tensor(np.array([5, 5])),
        paddle.to_tensor(np.array([7, 8])),
        paddle.to_tensor(np.array([1, 1])))
    np.testing.assert_array_equal(nodes.numpy(), [5, 7, 8])
    np.testing.assert_array_equal(dst.numpy(), [0, 0])
    np.testing.assert_array_equal(src.numpy(), [1, 2])


def test_fused_matmul_bias_batched_transpose():
    F = paddle.incubate.nn.functional
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 4).astype(np.float32)
    y = rs.randn(3, 5).astype(np.float32)
    out = F.fused_matmul_bias(paddle.to_tensor(x), paddle.to_tensor(y),
                              transpose_x=True)
    want = np.swapaxes(x, -1, -2) @ y
    np.testing.assert_allclose(out.numpy(), want, rtol=2e-2, atol=2e-2)


def test_fused_ec_moe_bf16():
    rs = np.random.RandomState(2)
    moe = paddle.incubate.nn.FusedEcMoe(8, 16, 2)
    x = paddle.to_tensor(rs.randn(1, 4, 8).astype(np.float32)) \
        .astype("bfloat16")
    g = paddle.to_tensor(rs.randn(1, 4, 2).astype(np.float32))
    y = moe(x, g)
    assert str(y.dtype) in ("paddle.bfloat16", "bfloat16") or \
        "bfloat16" in str(y.numpy().dtype)


def test_weight_quantize_reference_scale_convention():
    rs = np.random.RandomState(0)
    w = rs.randn(16, 8).astype(np.float32)
    q, s = paddle.nn.quant.weight_quantize(paddle.to_tensor(w))
    # reference convention: dequant = q * scale (scale = absmax/127)
    np.testing.assert_allclose(s.numpy(), np.abs(w).max(0) / 127.0,
                               rtol=1e-5)
    wd = paddle.nn.quant.weight_dequantize(q, s)
    assert np.abs(wd.numpy() - w).max() < np.abs(w).max() / 100


def test_dynamic_decode_zero_steps_raises():
    class _DoneDecoder(paddle.nn.Decoder):
        def initialize(self, inits):
            f = paddle.to_tensor(np.array([[True]]))
            return paddle.to_tensor([0]), None, f

        def step(self, *a, **kw):
            raise AssertionError("step must not run when all finished")

    with pytest.raises(ValueError, match="zero steps"):
        paddle.nn.dynamic_decode(_DoneDecoder(), inits=None,
                                 max_step_num=5)
