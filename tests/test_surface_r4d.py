"""Round-4d: fleet PS accessors/role makers, UtilBase, LocalFS,
profiler SummaryView, device.cuda props, prim toggles, and a trained
seq2seq beam-decode journey."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed.fleet as fleet


def test_role_makers(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                       "127.0.0.1:7164,127.0.0.1:7165")
    monkeypatch.setenv("POD_IP", "127.0.0.1")
    monkeypatch.setenv("PADDLE_PORT", "7165")
    rm = fleet.PaddleCloudRoleMaker(is_collective=False)
    assert rm.is_server() and not rm.is_worker()
    assert rm.server_index() == 1
    assert rm.server_num() == 2

    rm2 = fleet.UserDefinedRoleMaker(
        current_id=0, role=fleet.Role.WORKER, worker_num=2,
        server_endpoints=["127.0.0.1:7164"])
    assert rm2.is_worker() and rm2.worker_num() == 2
    assert rm2.get_pserver_endpoints() == ["127.0.0.1:7164"]


def test_fleet_ps_server_worker_roundtrip():
    from paddle_tpu.distributed.ps import PSServer, PSClient
    server = PSServer(port=0)
    server.create_dense_table("w", [4], rule="sgd", lr=0.1)
    client = PSClient([f"127.0.0.1:{server.port}"])
    before = np.asarray(client.pull_dense("w")).reshape(-1)
    np.testing.assert_allclose(before, np.zeros(4))
    client.push_dense("w", np.ones(4, np.float32))   # sgd: w -= lr*g
    got = np.asarray(client.pull_dense("w")).reshape(-1)
    np.testing.assert_allclose(got, -0.1 * np.ones(4), rtol=1e-6)
    client.close()


def test_fleet_accessors_collective_defaults():
    # no role maker registered -> collective behavior
    f = fleet.Fleet()
    assert f.is_worker() is True and f.is_server() is False
    assert f.server_num() == 0 and f.server_index() == -1
    assert f.server_endpoints() == []
    assert f.server_endpoints(to_string=True) == ""


def test_util_get_file_shard():
    u = fleet.UtilBase()
    files = [f"f{i}" for i in range(5)]
    # world size 1 in-process: full list
    assert u.get_file_shard(files) == files


def test_local_fs(tmp_path):
    fs = fleet.utils.LocalFS()
    d = tmp_path / "sub"
    fs.mkdirs(str(d))
    assert fs.is_dir(str(d)) and fs.is_exist(str(d))
    f = tmp_path / "a.txt"
    fs.touch(str(f))
    assert fs.is_file(str(f))
    dirs, files = fs.ls_dir(str(tmp_path))
    assert dirs == ["sub"] and files == ["a.txt"]
    fs.mv(str(f), str(tmp_path / "b.txt"))
    assert fs.is_exist(str(tmp_path / "b.txt"))
    fs.delete(str(d))
    assert not fs.is_exist(str(d))
    with pytest.raises(RuntimeError):
        fleet.utils.HDFSClient()


def test_meta_parallel_exports_and_sharding_wrapper():
    mp = fleet.meta_parallel
    assert hasattr(mp, "PipelineParallel")
    net = paddle.nn.Linear(4, 4)
    sp = mp.ShardingParallel(net)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    assert sp(x).shape == [2, 4]


def test_profiler_summary_view():
    import paddle_tpu.profiler as profiler
    assert profiler.SummaryView.KernelView.name == "KernelView"
    assert len(list(profiler.SummaryView)) >= 8


def test_device_cuda_props():
    cuda = paddle.device.cuda
    assert isinstance(cuda.get_device_name(), str)
    props = cuda.get_device_properties()
    assert props.name == cuda.get_device_name()
    assert cuda.get_device_capability() == (0, 0)
    with cuda.stream_guard(cuda.current_stream()):
        pass


def test_prim_toggles_and_incubate_grad():
    a = paddle.incubate.autograd
    assert not a.prim_enabled()
    a.enable_prim()
    try:
        assert a.prim_enabled()
    finally:
        a.disable_prim()
    assert not a.prim_enabled()
    x = paddle.to_tensor([3.0], stop_gradient=False)
    g = a.grad(x * x, x)
    gv = g[0] if isinstance(g, (list, tuple)) else g
    np.testing.assert_allclose(gv.numpy(), [6.0])
    with pytest.raises(NotImplementedError):
        a.forward_grad(None, None)


# -- trained seq2seq + beam decode journey ----------------------------------

def test_journey_lm_beam_decode_reproduces_pattern():
    """Train a GRU LM on a fixed token cycle, then BeamSearchDecoder must
    reproduce the cycle from the start token."""
    import paddle_tpu.nn as nn
    rs = np.random.RandomState(0)
    V, H = 6, 32
    pattern = [0, 2, 4, 1, 3, 5]       # 0 -> 2 -> 4 -> 1 -> 3 -> 5(end)
    nxt = {pattern[i]: pattern[i + 1] for i in range(len(pattern) - 1)}

    emb = nn.Embedding(V, H)
    cell = nn.GRUCell(H, H)
    head = nn.Linear(H, V)
    params = (list(emb.parameters()) + list(cell.parameters())
              + list(head.parameters()))
    opt = paddle.optimizer.Adam(0.01, parameters=params)

    xs = np.array([pattern[:-1]], np.int64)     # (1, 5)
    ys = np.array([pattern[1:]], np.int64)
    for step in range(150):
        h = paddle.to_tensor(np.zeros((1, H), np.float32))
        loss = paddle.to_tensor(0.0)
        for t in range(xs.shape[1]):
            e = emb(paddle.to_tensor(xs[:, t]))
            out, h = cell(e, h)
            logits = head(out)
            loss = loss + nn.functional.cross_entropy(
                logits, paddle.to_tensor(ys[:, t]))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < 0.1

    class _DecCell:
        def __call__(self, tok, h):
            out, h2 = cell(emb(tok), h)
            return head(out), h2

    dec = nn.BeamSearchDecoder(_DecCell(), start_token=0, end_token=5,
                               beam_size=2)
    init_h = paddle.to_tensor(np.zeros((1, H), np.float32))
    ids, fstate = nn.dynamic_decode(dec, inits=init_h, max_step_num=10)
    top = ids.numpy()[0, :, 0].tolist()
    assert top[:5] == pattern[1:], f"decoded {top}"


# -- review-fix regressions (r4d review) ------------------------------------

def test_bilinear_fills_all_channel_pairs():
    k = paddle.nn.initializer.Bilinear()((2, 1, 4, 4), "float32")
    arr = np.asarray(k)
    assert arr[1, 0].sum() > 0          # every out channel upsamples
    np.testing.assert_allclose(arr[0, 0], arr[1, 0])


def test_get_file_shard_uses_role_maker():
    from paddle_tpu.distributed.fleet.fleet import _FLEET
    rm = fleet.UserDefinedRoleMaker(current_id=1, role=fleet.Role.WORKER,
                                    worker_num=2)
    prev = _FLEET.get("role_maker")
    _FLEET["role_maker"] = rm
    try:
        got = fleet.UtilBase().get_file_shard(["a", "b", "c", "d", "e"])
        assert got == ["d", "e"]
    finally:
        _FLEET["role_maker"] = prev


def test_weight_quantize_group_size_rejected():
    w = paddle.to_tensor(np.ones((8, 4), np.float32))
    with pytest.raises(NotImplementedError):
        paddle.nn.quant.weight_quantize(w, group_size=128)
    q, s = paddle.nn.quant.weight_quantize(w)
    with pytest.raises(NotImplementedError):
        paddle.nn.quant.weight_only_linear(
            paddle.to_tensor(np.ones((2, 8), np.float32)), q,
            weight_scale=s, group_size=128)


def test_printoptions_sci_precision():
    paddle.set_printoptions(precision=2, sci_mode=True)
    try:
        r = repr(paddle.to_tensor([1.23456]))
        assert "1.23e+00" in r
    finally:
        paddle.set_printoptions(precision=8, sci_mode=False)


def test_localfs_touch_exist_ok(tmp_path):
    fs = fleet.utils.LocalFS()
    f = str(tmp_path / "m")
    fs.touch(f)
    with pytest.raises(FileExistsError):
        fs.touch(f, exist_ok=False)


def test_current_stream_singleton():
    cuda = paddle.device.cuda
    assert cuda.current_stream() is cuda.current_stream()


def test_save_inference_model_unknown_feed_raises(tmp_path):
    import paddle_tpu.static as static
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("image", [None, 4], "float32")
            out = paddle.tanh(x)
        with pytest.raises(KeyError, match="imge"):
            fleet.fleet.save_inference_model(
                None, str(tmp_path), ["imge"], [out], main_program=main)
    finally:
        paddle.disable_static()


def test_scalar_operands_stay_weakly_typed():
    # Python scalars must not upcast tensor dtypes (jnp weak typing) —
    # previously ensure_tensor(2.0) made an f32 device array which
    # promoted bf16 tensors to f32
    x = paddle.to_tensor(np.ones(4, np.float32)).astype("bfloat16")
    assert "bfloat16" in str((x * 2.0).dtype)
    assert "bfloat16" in str((x ** 2).dtype)
    assert "bfloat16" in str((2.0 - x).dtype)
    # gradients unchanged
    a = paddle.to_tensor([2.0], stop_gradient=False)
    ((a ** 2) + 3.0 * a - 1.0 / a).sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), [2 * 2.0 + 3.0 + 0.25])


def test_scalar_scalar_binary_still_works():
    # both operands scalar -> falls through to tensor path
    out = paddle.add(1.0, 2.0)
    assert float(out) == 3.0


# -- second review round fixes ----------------------------------------------

def test_role_maker_rejects_unlisted_server(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "10.0.0.1:7000")
    monkeypatch.setenv("POD_IP", "10.0.0.9")
    monkeypatch.setenv("PADDLE_PORT", "7000")
    with pytest.raises(ValueError, match="misconfigured"):
        fleet.PaddleCloudRoleMaker(is_collective=False)


def test_run_server_without_endpoints_raises():
    from paddle_tpu.distributed.fleet.fleet import _FLEET
    prev_rm = _FLEET.get("role_maker")
    prev_srv = _FLEET.pop("ps_server", None)
    _FLEET["role_maker"] = None
    try:
        with pytest.raises(RuntimeError, match="endpoints"):
            fleet.fleet.run_server()
    finally:
        _FLEET["role_maker"] = prev_rm
        if prev_srv is not None:
            _FLEET["ps_server"] = prev_srv


def test_localfs_mv_missing_src_and_dir_copy(tmp_path):
    fs = fleet.utils.LocalFS()
    with pytest.raises(FileNotFoundError):
        fs.mv(str(tmp_path / "nope"), str(tmp_path / "x"))
    d = tmp_path / "src_dir"
    d.mkdir()
    (d / "f.txt").write_text("hi")
    fs.upload(str(d), str(tmp_path / "dst_dir"))
    assert (tmp_path / "dst_dir" / "f.txt").read_text() == "hi"


def test_profiler_summary_accepts_views():
    import paddle_tpu.profiler as profiler
    p = profiler.Profiler()
    p.start()
    p.stop()
    out = p.summary(views=[profiler.SummaryView.KernelView])
    assert "Summary" in out


def test_mixed_precision_sidecar_roundtrip(tmp_path):
    import paddle_tpu.inference as inf
    src = tmp_path / "m.pdmodel"
    src.write_bytes(b"x")
    dst = tmp_path / "out" / "m.pdmodel"
    inf.convert_to_mixed_precision(str(src), None, str(dst), None,
                                   mixed_precision="bfloat16")
    cfg = inf.Config(str(dst))
    assert cfg._precision == "bfloat16"
    with pytest.raises(ValueError):
        inf.convert_to_mixed_precision(str(src), None, None, None)


def test_journey_fleet_ps_ctr_worker():
    """PS-mode CTR journey through the NEW fleet facade: server from a
    role maker, worker connects via fleet.init_worker, sparse embedding
    pulled/pushed each step, logistic loss falls."""
    from paddle_tpu.distributed.fleet.fleet import _FLEET
    from paddle_tpu.distributed.ps import PSServer

    # server side (in-process daemon): bind an ephemeral port first,
    # then hand its endpoint to the worker's role maker
    server = PSServer(port=0)
    server.create_sparse_table("emb", 8, rule="sgd", lr=0.5)
    endpoint = f"127.0.0.1:{server.port}"

    rm = fleet.UserDefinedRoleMaker(
        current_id=0, role=fleet.Role.WORKER, worker_num=1,
        server_endpoints=[endpoint])
    prev = _FLEET.get("role_maker")
    _FLEET["role_maker"] = rm
    try:
        client = fleet.fleet.init_worker()
        assert client is not None
        rs = np.random.RandomState(0)
        w_dense = np.zeros(8, np.float32)
        ids = np.arange(16)
        labels = (ids % 2).astype(np.float32)    # even ids -> 0, odd -> 1
        losses = []
        for step in range(60):
            emb = np.asarray(client.pull_sparse("emb", ids))  # (16, 8)
            logits = emb @ w_dense
            p = 1.0 / (1.0 + np.exp(-logits))
            losses.append(float(np.mean(
                -(labels * np.log(p + 1e-8)
                  + (1 - labels) * np.log(1 - p + 1e-8)))))
            dlogits = (p - labels) / len(ids)
            client.push_sparse("emb", ids, np.outer(dlogits, w_dense))
            w_dense -= 0.5 * emb.T @ dlogits
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
        fleet.fleet.stop_worker()
    finally:
        _FLEET["role_maker"] = prev


def test_launch_ps_mode_end_to_end(tmp_path):
    """fleetrun --run_mode ps: spawn 1 server + 2 trainers; each trainer
    pushes its rank-scaled gradient to a shared PS dense table; trainer 0
    verifies the accumulated value and writes a marker file."""
    import subprocess
    import sys
    import textwrap
    script = tmp_path / "ps_job.py"
    script.write_text(textwrap.dedent("""
        import os, time, json
        import numpy as np
        import paddle_tpu.distributed.fleet as fleet

        role = os.environ["TRAINING_ROLE"]
        if role == "PSERVER":
            fleet.init(is_collective=False)
            srv = fleet.fleet.init_server()
            srv.create_dense_table("w", [4], rule="sgd", lr=1.0)
            fleet.fleet.run_server()
        else:
            fleet.init(is_collective=False)
            tid = int(os.environ["PADDLE_TRAINER_ID"])
            client = None
            deadline = time.time() + 120   # server jax import can be
            while time.time() < deadline:  # slow on a contended core
                try:
                    client = fleet.fleet.init_worker()
                    client.pull_dense("w")
                    break
                except Exception:
                    client = None
                    time.sleep(0.5)
            assert client is not None, "could not reach PS server"
            g = np.full(4, float(tid + 1), np.float32)
            client.push_dense("w", g)
            time.sleep(1.0)          # let both pushes land
            if tid == 0:
                w = np.asarray(client.pull_dense("w")).reshape(-1)
                out = os.environ["PS_TEST_OUT"]
                with open(out, "w") as f:
                    json.dump({"w": w.tolist()}, f)
            fleet.fleet.stop_worker()
    """))
    import os
    out_file = tmp_path / "result.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PS_TEST_OUT"] = str(out_file)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # earlier suite tests may have leaked collective PADDLE_* vars into
    # this process; the launcher scrubs too, but keep the test hermetic
    for stale in list(env):
        if stale.startswith("PADDLE_") or stale == "TRAINING_ROLE":
            env.pop(stale)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--run_mode", "ps", "--server_num", "1", "--trainer_num", "2",
         "--log_dir", str(tmp_path / "log"), str(script)],
        env=env, timeout=300, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    import json
    w = json.load(open(out_file))["w"]
    # sgd lr=1.0: w = -(1+2) after both trainers pushed
    np.testing.assert_allclose(w, [-3.0] * 4)


def test_convert_to_mixed_precision_casts_params(tmp_path):
    """Real jit.save artifact: converted params payload is bf16 on disk,
    and jit.load casts back to the exported program dtypes so outputs
    still match."""
    import pickle
    import paddle_tpu.jit as jit
    import paddle_tpu.inference as inf
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    net = nn.Linear(4, 2)
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    want = net(paddle.to_tensor(x)).numpy()
    src = tmp_path / "m"
    jit.save(net, str(src), input_spec=[InputSpec([None, 4], "float32")])
    dst = tmp_path / "out" / "m"
    (tmp_path / "out").mkdir()
    inf.convert_to_mixed_precision(
        str(src) + ".pdmodel", str(src) + ".pdiparams",
        str(dst) + ".pdmodel", str(dst) + ".pdiparams",
        mixed_precision="bfloat16")
    with open(str(dst) + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    stored = {str(np.asarray(v).dtype) for v in meta["params"].values()}
    assert stored == {"bfloat16"}
    loaded = jit.load(str(dst))
    got = loaded(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_cuda_stream_guard_sets_current():
    cuda = paddle.device.cuda
    import paddle_tpu.device as device
    s = device.Stream()
    with cuda.stream_guard(s):
        assert device.current_stream() is s
    assert device.current_stream() is not s


def test_fleet_util_singleton():
    assert fleet.fleet.util is fleet.fleet.util
    assert fleet.fleet.util is fleet.util


def test_jit_save_polymorphic_batch(tmp_path):
    """None dims export symbolically: one artifact serves every batch
    size, and multi-input models share the batch symbol."""
    import paddle_tpu.jit as jit
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    jit.save(net, str(tmp_path / "m"),
             input_spec=[InputSpec([None, 4], "float32")])
    loaded = jit.load(str(tmp_path / "m"))
    for B in (1, 3, 17):
        x = np.random.RandomState(B).randn(B, 4).astype(np.float32)
        np.testing.assert_allclose(
            loaded(paddle.to_tensor(x)).numpy(),
            net(paddle.to_tensor(x)).numpy(), rtol=1e-5, atol=1e-5)

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l = nn.Linear(4, 2)

        def forward(self, a, b):
            return self.l(a + b)

    net2 = TwoIn()
    # r5 (ADVICE r4 #1): leading None dims are independent per input by
    # default; a model that COMBINES inputs along batch ties them
    # explicitly
    jit.save(net2, str(tmp_path / "m2"), tie_batch_dims=True,
             input_spec=[InputSpec([None, 4], "float32"),
                         InputSpec([None, 4], "float32")])
    loaded2 = jit.load(str(tmp_path / "m2"))
    a = np.ones((5, 4), np.float32)
    np.testing.assert_allclose(
        loaded2(paddle.to_tensor(a), paddle.to_tensor(a)).numpy(),
        net2(paddle.to_tensor(a), paddle.to_tensor(a)).numpy(),
        rtol=1e-5, atol=1e-5)
