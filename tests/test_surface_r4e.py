"""Round-4e: vision functional pad/affine, audio WAV IO, image backend,
paged block attention serving ops."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_transforms_functional_pad():
    import paddle_tpu.vision.transforms as T
    img = np.arange(48, dtype=np.uint8).reshape(4, 4, 3)
    assert T.pad(img, 1).shape == (6, 6, 3)
    assert T.pad(img, (1, 2)).shape == (8, 6, 3)
    assert T.pad(img, (1, 2, 3, 4)).shape == (10, 8, 3)
    np.testing.assert_array_equal(T.pad(img, 1, fill=7)[0, 0], [7, 7, 7])
    edge = T.pad(img, 1, padding_mode="edge")
    np.testing.assert_array_equal(edge[0, 1], img[0, 0])
    with pytest.raises(ValueError):
        T.pad(img, 1, padding_mode="weird")


def test_transforms_functional_affine_rotation():
    import paddle_tpu.vision.transforms as T
    img = np.zeros((5, 5), np.float32)
    img[1, 2] = 1.0                        # one pixel above center
    out = T.affine(img, angle=0, translate=(1, 0), scale=1.0, shear=0)
    assert out.shape == (5, 5)
    # pure translation moves the pixel right by 1
    assert out[1, 3] == 1.0
    ident = T.affine(img, angle=0, translate=(0, 0), scale=1.0, shear=0)
    np.testing.assert_allclose(ident, img)


def test_audio_wav_roundtrip(tmp_path):
    sr = 8000
    t = np.linspace(0, 1, sr, endpoint=False)
    wav = (0.5 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)[None]
    p = str(tmp_path / "a.wav")
    paddle.audio.save(p, paddle.to_tensor(wav), sr)
    info = paddle.audio.info(p)
    assert info.sample_rate == sr and info.num_channels == 1
    assert info.bits_per_sample == 16
    loaded, sr2 = paddle.audio.load(p)
    assert sr2 == sr
    np.testing.assert_allclose(loaded.numpy(), wav, atol=1e-3)
    # offset/num_frames window
    part, _ = paddle.audio.load(p, frame_offset=100, num_frames=50)
    np.testing.assert_allclose(part.numpy(), wav[:, 100:150], atol=1e-3)
    # channels_first=False
    tc, _ = paddle.audio.load(p, channels_first=False)
    assert tc.shape == [sr, 1]


def test_audio_backend_registry():
    b = paddle.audio.backends
    assert b.get_current_audio_backend() == "wave"
    assert "wave" in b.list_available_backends()
    with pytest.raises(ValueError):
        b.set_backend("soundfile")


def test_image_backend(tmp_path):
    from PIL import Image
    assert paddle.vision.get_image_backend() == "pil"
    with pytest.raises(ValueError):
        paddle.vision.set_image_backend("cv2")
    p = str(tmp_path / "i.png")
    Image.fromarray(np.zeros((4, 6, 3), np.uint8)).save(p)
    img = paddle.vision.image_load(p)
    assert img.size == (6, 4)


# -- paged block attention --------------------------------------------------

def _dense_causal(q, k, v, D):
    s = np.einsum("nhd,lhd->hnl", q, k) / np.sqrt(D)
    n, L = s.shape[1], s.shape[2]
    cm = np.arange(L)[None, None, :] <= \
        (L - n + np.arange(n))[None, :, None]
    s = np.where(cm, s, -1e9)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("hnl,lhd->nhd", p, v)


def test_blha_get_max_len():
    F = paddle.incubate.nn.functional
    me, md = F.blha_get_max_len(paddle.to_tensor([5, 3]),
                                paddle.to_tensor([0, 7]), 2)
    assert int(me) == 5 and int(md) == 7


def test_block_multihead_attention_prefill_then_decode():
    F = paddle.incubate.nn.functional
    rs = np.random.RandomState(0)
    B, H, D, bs, n_blocks = 2, 2, 8, 4, 8
    enc = np.array([5, 3])
    dec = np.zeros(2, np.int64)
    this = np.array([5, 3])
    qkv = rs.randn(8, 3 * H * D).astype(np.float32)
    kc = paddle.to_tensor(np.zeros((n_blocks, H, bs, D), np.float32))
    vc = paddle.to_tensor(np.zeros((n_blocks, H, bs, D), np.float32))
    bt = paddle.to_tensor(np.array([[0, 1, -1], [2, 3, -1]]))
    out, _, kc, vc = F.block_multihead_attention(
        paddle.to_tensor(qkv), kc, vc, paddle.to_tensor(enc),
        paddle.to_tensor(dec), paddle.to_tensor(this),
        block_tables=bt, block_size=bs)
    q3 = qkv.reshape(8, 3, H, D)
    ref0 = _dense_causal(q3[:5, 0], q3[:5, 1], q3[:5, 2], D) \
        .reshape(5, H * D)
    ref1 = _dense_causal(q3[5:, 0], q3[5:, 1], q3[5:, 2], D) \
        .reshape(3, H * D)
    np.testing.assert_allclose(out.numpy()[:5], ref0, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(out.numpy()[5:], ref1, rtol=2e-2, atol=2e-2)

    # decode step: one new token per row over the paged cache
    qkv2 = rs.randn(2, 3 * H * D).astype(np.float32)
    out2, _, kc, vc = F.block_multihead_attention(
        paddle.to_tensor(qkv2), kc, vc,
        paddle.to_tensor(np.zeros(2, np.int64)),
        paddle.to_tensor(np.array([5, 3])),
        paddle.to_tensor(np.array([1, 1])),
        block_tables=bt, block_size=bs)
    q3b = qkv2.reshape(2, 3, H, D)
    kall = np.concatenate([q3[:5, 1], q3b[0:1, 1]], 0)
    vall = np.concatenate([q3[:5, 2], q3b[0:1, 2]], 0)
    ref_d = _dense_causal(q3b[0:1, 0], kall, vall, D).reshape(1, H * D)
    np.testing.assert_allclose(out2.numpy()[:1], ref_d, rtol=2e-2,
                               atol=2e-2)


def test_block_multihead_attention_rejects_unsupported():
    F = paddle.incubate.nn.functional
    with pytest.raises(ValueError):
        F.block_multihead_attention(
            paddle.to_tensor(np.zeros((1, 6), np.float32)), None, None,
            paddle.to_tensor([1]), paddle.to_tensor([0]),
            paddle.to_tensor([1]))
    with pytest.raises(NotImplementedError):
        F.block_multihead_attention(
            paddle.to_tensor(np.zeros((1, 6), np.float32)), None, None,
            paddle.to_tensor([1]), paddle.to_tensor([0]),
            paddle.to_tensor([1]),
            block_tables=paddle.to_tensor([[0]]), rope_emb=object())


# -- review-fix regressions (r4e review) ------------------------------------

def test_block_mha_additive_mask_semantics():
    F = paddle.incubate.nn.functional
    rs = np.random.RandomState(1)
    H, D, bs = 1, 4, 4
    qkv = rs.randn(3, 3 * H * D).astype(np.float32)
    kc = paddle.to_tensor(np.zeros((2, H, bs, D), np.float32))
    vc = paddle.to_tensor(np.zeros((2, H, bs, D), np.float32))
    bt = paddle.to_tensor(np.array([[0, 1]]))
    args = (paddle.to_tensor(qkv), kc, vc, paddle.to_tensor([3]),
            paddle.to_tensor([0]), paddle.to_tensor([3]))
    out_nomask, _, _, _ = F.block_multihead_attention(
        *args, block_tables=bt, block_size=bs)
    # an all-zero ADDITIVE mask must be a no-op
    zmask = paddle.to_tensor(np.zeros((1, 1, 3, 3), np.float32))
    out_zmask, _, _, _ = F.block_multihead_attention(
        *args, block_tables=bt, block_size=bs, mask=zmask)
    np.testing.assert_allclose(out_nomask.numpy(), out_zmask.numpy(),
                               rtol=1e-5)
    with pytest.raises(ValueError, match="additive"):
        F.block_multihead_attention(
            *args, block_tables=bt, block_size=bs,
            mask=paddle.to_tensor(np.zeros((3, 3), np.float32)))


def test_block_mha_rejects_unknown_kwargs():
    F = paddle.incubate.nn.functional
    with pytest.raises(NotImplementedError, match="qkv_out_scale"):
        F.block_multihead_attention(
            paddle.to_tensor(np.zeros((1, 12), np.float32)),
            paddle.to_tensor(np.zeros((1, 1, 4, 4), np.float32)),
            paddle.to_tensor(np.zeros((1, 1, 4, 4), np.float32)),
            paddle.to_tensor([1]), paddle.to_tensor([0]),
            paddle.to_tensor([1]),
            block_tables=paddle.to_tensor([[0]]),
            qkv_out_scale=1.0)


def test_audio_save_1d_channels_last(tmp_path):
    p = str(tmp_path / "m.wav")
    paddle.audio.save(p, np.zeros(100, np.float32), 8000,
                      channels_first=False)
    info = paddle.audio.info(p)
    assert info.num_channels == 1 and info.num_samples == 100


def test_pad_per_channel_fill():
    import paddle_tpu.vision.transforms as T
    img = np.zeros((2, 2, 3), np.uint8)
    out = T.pad(img, 1, fill=(255, 7, 3))
    np.testing.assert_array_equal(out[0, 0], [255, 7, 3])
    with pytest.raises(ValueError):
        T.pad(np.zeros((2, 2), np.uint8), 1, fill=(1, 2, 3))


def test_affine_shear_direction():
    import paddle_tpu.vision.transforms as T
    img = np.zeros((7, 7), np.float32)
    img[1, 3] = 1.0                    # above center
    out = T.affine(img, angle=0, translate=(0, 0), scale=1.0, shear=30.0)
    ys, xs = np.nonzero(out > 0.25)
    # +x shear moves content ABOVE center toward +x... reference
    # convention: forward matrix [[1, tan], [0, 1]] maps (x, y)->(x+ty, y)
    # with y measured from center (negative above) -> moves LEFT above
    assert xs.min() < 3, (ys, xs)


def test_audio_load_native_int_and_save_clip(tmp_path):
    p = str(tmp_path / "n.wav")
    paddle.audio.save(p, np.array([[0.5, -0.5]], np.float32), 8000)
    raw, _ = paddle.audio.load(p, normalize=False)
    assert raw.numpy().dtype == np.int16        # native dtype, not float
    # out-of-range int input clips instead of wrapping
    p2 = str(tmp_path / "c.wav")
    paddle.audio.save(p2, np.array([[40000, -40000]], np.int32), 8000)
    back, _ = paddle.audio.load(p2, normalize=False)
    np.testing.assert_array_equal(back.numpy(), [[32767, -32768]])


def test_block_mha_rejects_unallocated_block():
    F = paddle.incubate.nn.functional
    rs = np.random.RandomState(0)
    H, D, bs = 1, 4, 4
    # 9 tokens need 3 blocks; the table only allocates 2 (then -1)
    qkv = rs.randn(9, 3 * H * D).astype(np.float32)
    kc = paddle.to_tensor(np.zeros((4, H, bs, D), np.float32))
    vc = paddle.to_tensor(np.zeros((4, H, bs, D), np.float32))
    bt = paddle.to_tensor(np.array([[0, 1, -1]]))
    with pytest.raises(ValueError, match="no allocated block"):
        F.block_multihead_attention(
            paddle.to_tensor(qkv), kc, vc, paddle.to_tensor([9]),
            paddle.to_tensor([0]), paddle.to_tensor([9]),
            block_tables=bt, block_size=bs)


def test_pad_class_delegates_to_functional():
    import paddle_tpu.vision.transforms as T
    img = np.zeros((2, 2, 3), np.uint8)
    out = T.Pad(1, fill=9)(img)
    np.testing.assert_array_equal(out[0, 0], [9, 9, 9])
    out4 = T.Pad((1, 2, 3, 4))(img)
    assert out4.shape == (8, 6, 3)
    refl = T.Pad(1, padding_mode="edge")(img)
    assert refl.shape == (4, 4, 3)


def test_fused_multi_head_attention_functional():
    F = paddle.incubate.nn.functional
    rs = np.random.RandomState(0)
    B, S, H, Dh = 2, 4, 2, 8
    C = H * Dh
    x = rs.randn(B, S, C).astype(np.float32)
    wq = rs.randn(3, H, Dh, C).astype(np.float32) * 0.1
    wl = rs.randn(C, C).astype(np.float32) * 0.1
    lns, lnb = np.ones(C, np.float32), np.zeros(C, np.float32)
    out = F.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wq), paddle.to_tensor(wl),
        ln_scale=paddle.to_tensor(lns), ln_bias=paddle.to_tensor(lnb),
        dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
    qkv = np.einsum("bsc,thdc->bsthd", x, wq)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(Dh)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, S, C) @ wl
    ref = x + o
    mu, var = ref.mean(-1, keepdims=True), ref.var(-1, keepdims=True)
    ref = (ref - mu) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-2, atol=2e-2)
    # (C, 3C) packed layout agrees with the reference-native layout
    wq_t = wq.transpose(3, 0, 1, 2).reshape(C, 3 * C)
    out2 = F.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wq_t), paddle.to_tensor(wl),
        ln_scale=paddle.to_tensor(lns), ln_bias=paddle.to_tensor(lnb),
        dropout_rate=0.0, attn_dropout_rate=0.0, training=False,
        transpose_qkv_wb=True, num_heads=H)
    np.testing.assert_allclose(out2.numpy(), out.numpy(), rtol=2e-2,
                               atol=2e-2)
    with pytest.raises(NotImplementedError):
        F.fused_multi_head_attention(
            paddle.to_tensor(x), paddle.to_tensor(wq),
            paddle.to_tensor(wl), cache_kv=paddle.to_tensor(x))


def test_journey_train_save_serve_pipeline(tmp_path):
    """Capstone: train eagerly -> jit.save (polymorphic batch) ->
    inference Config/create_predictor -> serve at several batch sizes,
    outputs matching the live model."""
    import paddle_tpu.jit as jit
    import paddle_tpu.inference as inference
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    rs = np.random.RandomState(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    opt = paddle.optimizer.Adam(0.05, parameters=net.parameters())
    X = rs.randn(64, 8).astype(np.float32)
    w_true = rs.randn(8, 2).astype(np.float32)
    Y = X @ w_true
    for _ in range(40):
        loss = ((net(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2) \
            .mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < 1.0

    prefix = str(tmp_path / "served")
    jit.save(net, prefix, input_spec=[InputSpec([None, 8], "float32")])
    cfg = inference.Config(prefix)
    predictor = inference.create_predictor(cfg)
    for B in (1, 5, 32):
        xb = rs.randn(B, 8).astype(np.float32)
        got = predictor.run([xb])[0]
        want = net(paddle.to_tensor(xb)).numpy()
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=1e-4)


def test_fused_mha_bool_mask_and_dropout_mode():
    F = paddle.incubate.nn.functional
    rs = np.random.RandomState(3)
    B, S, H, Dh = 1, 4, 1, 8
    C = H * Dh
    x = rs.randn(B, S, C).astype(np.float32)
    wq = rs.randn(3, H, Dh, C).astype(np.float32) * 0.1
    wl = np.eye(C, dtype=np.float32)
    # bool mask masking the last key must differ from no mask, and match
    # the additive -inf form
    bmask = np.ones((B, H, S, S), bool)
    bmask[..., -1] = False
    amask = np.where(bmask, 0.0, -1e9).astype(np.float32)
    kw = dict(dropout_rate=0.0, attn_dropout_rate=0.0, training=False,
              add_residual=False)
    o_bool = F.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wq), paddle.to_tensor(wl),
        attn_mask=paddle.to_tensor(bmask), **kw)
    o_add = F.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wq), paddle.to_tensor(wl),
        attn_mask=paddle.to_tensor(amask), **kw)
    np.testing.assert_allclose(o_bool.numpy(), o_add.numpy(), rtol=1e-4)
    o_none = F.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wq), paddle.to_tensor(wl),
        **kw)
    assert np.abs(o_bool.numpy() - o_none.numpy()).max() > 1e-5
    # downscale_in_infer: inference output scales by (1-p).  Post-LN is
    # scale-invariant, so observe it on the pre-LN path (no trailing LN)
    kw_pre = dict(attn_dropout_rate=0.0, training=False,
                  add_residual=False, pre_layer_norm=True)
    o_pre = F.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wq), paddle.to_tensor(wl),
        dropout_rate=0.0, **kw_pre)
    o_down = F.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wq), paddle.to_tensor(wl),
        dropout_rate=0.5, mode="downscale_in_infer", **kw_pre)
    np.testing.assert_allclose(o_down.numpy(), 0.5 * o_pre.numpy(),
                               rtol=1e-4)
    with pytest.raises(ValueError, match="mode"):
        F.fused_multi_head_attention(
            paddle.to_tensor(x), paddle.to_tensor(wq),
            paddle.to_tensor(wl), mode="bogus")


def test_static_nn_prelu_element_mode():
    import paddle_tpu.static as static
    import paddle_tpu.static.nn as snn
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 2, 3], "float32")
            out = snn.prelu(x, mode="element")
        exe = static.Executor()
        xv = np.array([[[1.0, -2.0, 3.0], [-4.0, 5.0, -6.0]]], np.float32)
        got = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
        want = np.where(xv >= 0, xv, 0.25 * xv)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)
        with pytest.raises(ValueError):
            snn.prelu(x, mode="bogus")
    finally:
        paddle.disable_static()


def test_static_nn_prelu_element_dynamic_dim_raises():
    import paddle_tpu.static as static
    import paddle_tpu.static.nn as snn
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("xd", [None, -1, 8], "float32")
            with pytest.raises(ValueError, match="concrete"):
                snn.prelu(x, mode="element")
    finally:
        paddle.disable_static()


def test_static_nn_prelu_channel_dynamic_raises():
    import paddle_tpu.static as static
    import paddle_tpu.static.nn as snn
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("xc", [None, -1, 4, 4], "float32")
            with pytest.raises(ValueError, match="channel"):
                snn.prelu(x, mode="channel")
    finally:
        paddle.disable_static()
