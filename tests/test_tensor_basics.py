import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])
    assert str(x.dtype) == "float32"


def test_arithmetic_and_broadcast():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([[1.0], [2.0]])
    c = a + b
    assert c.shape == [2, 3]
    np.testing.assert_allclose((a * 2 + 1).numpy(), [3, 5, 7])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((1 / a).numpy(), 1 / np.array([1., 2., 3.]))


def test_reductions():
    x = paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4))
    assert float(paddle.sum(x)) == 66.0
    np.testing.assert_allclose(paddle.mean(x, axis=0).numpy(),
                               np.arange(12.).reshape(3, 4).mean(0))
    v, idx = paddle.topk(x, k=2, axis=1)
    assert v.shape == [3, 2]
    np.testing.assert_allclose(idx.numpy(), [[3, 2]] * 3)


def test_manipulation():
    x = paddle.arange(24, dtype="float32").reshape([2, 3, 4])
    y = paddle.transpose(x, [2, 0, 1])
    assert y.shape == [4, 2, 3]
    z = paddle.concat([x, x], axis=1)
    assert z.shape == [2, 6, 4]
    parts = paddle.split(z, 2, axis=1)
    assert len(parts) == 2 and parts[0].shape == [2, 3, 4]
    np.testing.assert_allclose(parts[0].numpy(), x.numpy())
    s = paddle.squeeze(paddle.unsqueeze(x, 0), 0)
    assert s.shape == x.shape


def test_backward_simple():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_backward_chain_and_accumulation():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    z = y * y + y  # dz/dx = (2y*3 + 3) = 18x + 3... via chain
    z.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 18 * np.array([1., 2.]) + 3)


def test_backward_shared_input():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * x + x * x  # x used by two branches
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 8.0])


def test_grad_api():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), 6.0)
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_no_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y._node is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    z = y * 3
    z.backward()
    assert x.grad is None


def test_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []
    x.register_hook(lambda g: seen.append(g.numpy()))
    (x * 5).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [5.0])


def test_matmul_grad():
    a = paddle.to_tensor(np.random.rand(3, 4).astype("float32"),
                         stop_gradient=False)
    b = paddle.to_tensor(np.random.rand(4, 5).astype("float32"),
                         stop_gradient=False)
    out = paddle.matmul(a, b).sum()
    out.backward()
    np.testing.assert_allclose(a.grad.numpy(),
                               np.ones((3, 5)) @ b.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(),
                               a.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_indexing_grad():
    x = paddle.to_tensor(np.arange(6, dtype="float32"), stop_gradient=False)
    y = x[2:5].sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 0, 1, 1, 1, 0])


def test_cast_and_dtype():
    x = paddle.to_tensor([1, 2, 3])
    assert str(x.dtype) == "int64" or str(x.dtype) == "int32"
    y = x.astype("float32")
    assert str(y.dtype) == "float32"


def test_multi_output_op_grads():
    x = paddle.to_tensor(np.random.rand(4, 6).astype("float32"),
                         stop_gradient=False)
    parts = paddle.split(x, 2, axis=1)
    loss = parts[0].sum() + (parts[1] * 2).sum()
    loss.backward()
    expect = np.concatenate([np.ones((4, 3)), 2 * np.ones((4, 3))], axis=1)
    np.testing.assert_allclose(x.grad.numpy(), expect)


def test_where_and_comparison():
    x = paddle.to_tensor([1.0, -2.0, 3.0])
    out = paddle.where(x > 0, x, paddle.zeros_like(x))
    np.testing.assert_allclose(out.numpy(), [1, 0, 3])


def test_einsum():
    a = paddle.to_tensor(np.random.rand(2, 3).astype("float32"))
    b = paddle.to_tensor(np.random.rand(3, 4).astype("float32"))
    out = paddle.einsum("ij,jk->ik", a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(),
                               rtol=1e-5)
