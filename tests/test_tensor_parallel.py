"""TP parity: mp_degree>1 run == single-device goldens (the reference's
hybrid_parallel_mp_model.py pattern)."""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, get_rng_state_tracker)


class MPBlock(nn.Layer):
    """Embedding → column-parallel → gelu → row-parallel (one Megatron MLP)."""

    def __init__(self, vocab=32, hidden=16, ffn=32):
        super().__init__()
        self.emb = VocabParallelEmbedding(vocab, hidden)
        self.up = ColumnParallelLinear(hidden, ffn, gather_output=False)
        self.act = nn.GELU()
        self.down = RowParallelLinear(ffn, hidden, input_is_parallel=True)

    def forward(self, ids):
        return self.down(self.act(self.up(self.emb(ids))))


class PlainBlock(nn.Layer):
    def __init__(self, vocab=32, hidden=16, ffn=32):
        super().__init__()
        self.emb = nn.Embedding(vocab, hidden)
        self.up = nn.Linear(hidden, ffn)
        self.act = nn.GELU()
        self.down = nn.Linear(ffn, hidden)

    def forward(self, ids):
        return self.down(self.act(self.up(self.emb(ids))))


def _sync_weights(src, dst):
    """Copy src (plain) weights into dst (mp) — same logical shapes."""
    dst.emb.weight.set_value(src.emb.weight)
    dst.up.weight.set_value(src.up.weight)
    dst.up.bias.set_value(src.up.bias)
    dst.down.weight.set_value(src.down.weight)
    dst.down.bias.set_value(src.down.bias)


def test_tp2_forward_backward_parity():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    golden = PlainBlock()
    mp = MPBlock()
    _sync_weights(golden, mp)
    dmp = fleet.distributed_model(mp)
    assert dmp._placement_plan is not None

    ids = np.random.RandomState(0).randint(0, 32, (8, 6)).astype("i8")
    tgt = np.random.RandomState(1).rand(8, 6, 16).astype("f4")

    model = paddle.Model(dmp)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=mp.parameters())
    model.prepare(opt, nn.MSELoss())

    gmodel = paddle.Model(golden)
    gopt = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=golden.parameters())
    gmodel.prepare(gopt, nn.MSELoss())

    for step in range(3):
        res = model.train_batch([ids], [tgt])
        gres = gmodel.train_batch([ids], [tgt])
        np.testing.assert_allclose(res[0], gres[0], rtol=2e-4, atol=1e-5)

    # TP weights are sharded on the model axis
    up_w = mp.up.weight._value
    assert not up_w.sharding.is_fully_replicated
    # logical values still match the golden after steps
    np.testing.assert_allclose(np.asarray(up_w),
                               golden.up.weight.numpy(), rtol=2e-4,
                               atol=1e-5)


def test_parallel_cross_entropy_matches():
    logits = np.random.RandomState(0).randn(4, 7, 32).astype("f4")
    labels = np.random.RandomState(1).randint(0, 32, (4, 7)).astype("i8")
    pce = ParallelCrossEntropy()
    out = pce(paddle.to_tensor(logits), paddle.to_tensor(labels))
    import paddle_tpu.nn.functional as F
    ref = F.cross_entropy(paddle.to_tensor(logits),
                          paddle.to_tensor(labels), reduction="none")
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)


def test_rng_tracker_streams_deterministic():
    tr = get_rng_state_tracker()
    tr.reset()
    tr.add("model_parallel_rng", 123)
    with tr.rng_state("model_parallel_rng"):
        a = paddle.randn([4]).numpy()
    tr.reset()
    tr.add("model_parallel_rng", 123)
    with tr.rng_state("model_parallel_rng"):
        b = paddle.randn([4]).numpy()
    np.testing.assert_allclose(a, b)
    # successive draws from the same stream differ
    with tr.rng_state("model_parallel_rng"):
        c = paddle.randn([4]).numpy()
    assert not np.allclose(b, c)
