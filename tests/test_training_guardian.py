"""Training guardian suite (ISSUE 2 tentpole harness): numeric sentinel,
skip-and-rollback escalation ladder, DP-lockstep verdicts, fused
GradScaler.unscale_, and the collective watchdog — every trip path driven
deterministically by failpoints.

Acceptance anchors:
- NaN gradient mid-``Model.fit`` → skip; repeated trips → rollback to the
  last COMMITTED checkpoint (PR 1 protocol) and training completes with a
  finite final loss, fully automatic.
- ``GradScaler.unscale_`` issues exactly ONE host sync per step
  regardless of parameter count (counting shim on guardian._host_bool).
- Guardian disabled: hook sites pay one truthiness check (sentinel gate
  is a module-level None check, like failpoints' _ACTIVE dict).
"""
import math
import os
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import amp
from paddle_tpu.framework import failpoints, guardian
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import collective
from paddle_tpu.hapi import callbacks as cbks_mod
from paddle_tpu.static import InputSpec

pytestmark = [pytest.mark.chaos, pytest.mark.guardian]


@pytest.fixture(autouse=True)
def _clean_guardian():
    failpoints.clear()
    guardian.clear_events()
    guardian.uninstall_sentinel()
    guardian.track_collectives(False)
    yield
    failpoints.clear()
    guardian.clear_events()
    guardian.uninstall_sentinel()
    guardian.track_collectives(False)


# -- sentinel primitives --------------------------------------------------

class TestSentinelPrimitives:
    def test_tree_all_finite(self):
        ok = guardian.tree_all_finite([jnp.ones(4), jnp.zeros((2, 3))])
        assert bool(ok)
        bad = guardian.tree_all_finite(
            [jnp.ones(4), jnp.asarray([1.0, float("inf")])])
        assert not bool(bad)
        # non-floating and None leaves pass vacuously
        assert bool(guardian.tree_all_finite(
            [jnp.arange(3), None]))
        assert bool(guardian.tree_all_finite([]))

    def test_attribution_names_offenders_with_stats(self):
        grads = [("clean", jnp.ones(4)),
                 ("poisoned", jnp.asarray([1.0, float("nan"),
                                           float("inf"), 2.0]))]
        offenders = guardian.attribute_nonfinite(grads, step=7)
        assert offenders == ["poisoned"]
        (ev,) = guardian.events("sentinel_trip")
        assert ev["step"] == 7 and ev["tensor"] == "poisoned"
        assert ev["nan_count"] == 1 and ev["inf_count"] == 1
        assert ev["finite_absmax"] == 2.0

    def test_emit_rejects_schema_drift(self):
        with pytest.raises(ValueError, match="schema"):
            guardian.emit("loss_spike", step=1, loss=2.0)  # missing fields
        bogus = "not_an_" + "event"   # built, so the schema lint skips it
        with pytest.raises(ValueError, match="unknown"):
            guardian.emit(bogus, foo=1)

    def test_guardian_log_jsonl_sink(self, tmp_path, monkeypatch):
        path = str(tmp_path / "guardian.jsonl")
        monkeypatch.setenv("PADDLE_GUARDIAN_LOG", path)
        guardian.emit("loss_spike", step=1, loss=9.0, ema=1.0, zscore=8.0)
        import json
        with open(path) as f:
            rec = json.loads(f.read().strip())
        assert rec["event"] == "loss_spike" and rec["zscore"] == 8.0
        assert "ts_ns" in rec and "rank" in rec


class TestLossSpikeDetector:
    def test_no_trip_during_warmup_or_steady_state(self):
        det = guardian.LossSpikeDetector(warmup=5, zscore=6.0)
        rng = np.random.RandomState(0)
        assert not any(det.update(1.0 + 0.01 * rng.randn())
                       for _ in range(50))

    def test_trips_on_spike_without_absorbing_it(self):
        det = guardian.LossSpikeDetector(warmup=5, zscore=6.0)
        for _ in range(20):
            det.update(1.0)
        ema_before = det.ema
        assert det.update(100.0)              # spike trips...
        assert det.ema == ema_before          # ...and is NOT absorbed

    def test_nonfinite_loss_always_trips(self):
        det = guardian.LossSpikeDetector(warmup=5)
        assert det.update(float("nan"))
        assert det.update(float("inf"))

    def test_plateaued_loss_tolerates_epsilon_noise(self):
        # var≈0 on a flat loss must not let sub-epsilon noise z-explode
        det = guardian.LossSpikeDetector(warmup=5, zscore=6.0)
        for _ in range(20):
            det.update(1.0)
        assert not det.update(1.0000001)     # noise, not a spike
        assert det.update(100.0)             # a real spike still trips


# -- fused GradScaler.unscale_ --------------------------------------------

def _params_with_grads(n, poison_idx=None):
    ps = []
    for i in range(n):
        p = paddle.nn.Linear(4, 4).parameters()[0]
        g = jnp.ones_like(p._value)
        if i == poison_idx:
            g = g.at[0, 0].set(jnp.nan)
        p._grad = g
        ps.append(p)
    return ps


class _Opt:
    def __init__(self, params):
        self._parameter_list = params


class TestGradScalerFused:
    def test_found_inf_detected_and_grads_unscaled(self):
        scaler = amp.GradScaler(init_loss_scaling=4.0,
                                use_dynamic_loss_scaling=True)
        opt = _Opt(_params_with_grads(3, poison_idx=1))
        scaler.unscale_(opt)
        assert scaler._found_inf
        # clean grads really were unscaled by 1/4
        g = np.asarray(opt._parameter_list[0]._grad)
        np.testing.assert_allclose(g, 0.25)

    def test_exactly_one_host_sync_any_param_count(self):
        # acceptance: ONE host sync per unscale_ regardless of #params —
        # the counting shim is guardian._host_bool, the single funnel
        # every sentinel verdict readback goes through
        for n in (1, 5, 17):
            scaler = amp.GradScaler(init_loss_scaling=2.0)
            opt = _Opt(_params_with_grads(n))
            before = guardian.host_sync_count()
            scaler.unscale_(opt)
            assert guardian.host_sync_count() - before == 1, \
                f"{n} params must cost exactly one host sync"
            assert not scaler._found_inf

    def test_step_skips_update_on_found_inf(self):
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        scaler = amp.GradScaler(init_loss_scaling=2.0)
        w0 = np.asarray(net.parameters()[0]._value).copy()
        for p in opt._parameter_list:
            p._grad = jnp.full_like(p._value, jnp.nan)
        scaler.unscale_(opt)
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(
            np.asarray(net.parameters()[0]._value), w0)


# -- DP lockstep verdicts -------------------------------------------------

class TestDataParallelLockstep:
    def test_all_reduce_finite_pmin_across_ranks(self):
        # one rank's NaN must flip EVERY rank's verdict (pmin over the
        # dp axis) so replicas skip in lockstep instead of diverging
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        assert jax.device_count() == 8
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("dp",))
        group = collective.new_group(axis_name="dp")
        per_rank = jnp.asarray([[1.0], [float("nan")]])  # rank1 poisoned

        def verdict(g):
            local = guardian.tree_all_finite([g])
            return guardian.all_reduce_finite(
                local, group).astype(jnp.int32).reshape(1)

        out = shard_map(verdict, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(per_rank)
        np.testing.assert_array_equal(np.asarray(out), [0, 0])

    def test_all_reduce_finite_identity_outside_trace(self):
        group = collective.new_group(axis_name="dp")
        flag = jnp.asarray(False)
        assert not bool(guardian.all_reduce_finite(flag, group))
        assert bool(guardian.all_reduce_finite(jnp.asarray(True), None))


# -- eager optimizer sentinel rung ----------------------------------------

class TestEagerSentinel:
    def test_optimizer_step_skips_on_nan_grad(self):
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=net.parameters())
        sentinel = guardian.NumericSentinel(guardian.GuardianConfig())
        guardian.install_sentinel(sentinel)
        w0 = np.asarray(net.parameters()[0]._value).copy()
        for p in opt._parameter_list:
            p._grad = jnp.full_like(p._value, jnp.nan)
        opt.step()
        np.testing.assert_array_equal(
            np.asarray(net.parameters()[0]._value), w0)  # update skipped
        assert guardian.events("sentinel_trip")          # and attributed

    def test_gate_is_single_none_check_when_disabled(self):
        assert guardian._SENTINEL is None   # the zero-cost contract
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        for p in opt._parameter_list:
            p._grad = jnp.ones_like(p._value)
        opt.step()                          # unguarded path still steps
        assert not guardian.events()


# -- the fit escalation ladder --------------------------------------------

def _reg_model(seed=3):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model = paddle.Model(net, inputs=[InputSpec([None, 4], "float32", "x")],
                         labels=[InputSpec([None, 2], "float32", "y")])
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    model.prepare(opt, nn.MSELoss())
    return model


def _batches(n=30, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(8, 4).astype("float32"),
             rng.randn(8, 2).astype("float32")) for _ in range(n)]


class _ArmAt(cbks_mod.Callback):
    """Arm a failpoint at a given train step (deterministic mid-fit)."""

    def __init__(self, at_step, name, action):
        super().__init__()
        self.at_step, self.name, self.action = at_step, name, action

    def on_train_batch_end(self, step, logs=None):
        if step == self.at_step:
            failpoints.set_failpoint(self.name, self.action)


class TestFitEscalationLadder:
    def test_single_nan_batch_is_skipped_params_stay_finite(self, tmp_path):
        model = _reg_model()
        cfg = guardian.GuardianConfig(skip_limit=3, ckpt_root=None,
                                      loss_spike=False)
        model.fit(_batches(12), epochs=1, verbose=0, guardian=cfg,
                  callbacks=[_ArmAt(3, "guardian.poison_batch", "skip*1")])
        skips = guardian.events("skip_step")
        assert len(skips) == 1 and skips[0]["reason"] == "nonfinite"
        trips = guardian.events("sentinel_trip")   # jit-path attribution
        assert trips and all(t["nan_count"] > 0 for t in trips)
        for k, v in model.network.state_dict().items():
            assert np.isfinite(np.asarray(v._value)).all(), k

    def test_repeated_trips_roll_back_to_last_committed(self, tmp_path):
        # the acceptance chaos scenario: NaN grads mid-fit → skip, skip,
        # then rollback to the last COMMITTED PR-1 checkpoint, skip the
        # poisoned window, and complete training — fully automatic
        root = str(tmp_path / "guard_ckpts")
        model = _reg_model()
        cfg = guardian.GuardianConfig(skip_limit=2, skip_window=2,
                                      ckpt_every=5, ckpt_root=root,
                                      spike_warmup=5)
        model.fit(_batches(30), epochs=1, verbose=0, guardian=cfg,
                  callbacks=[_ArmAt(9, "guardian.poison_batch", "skip*5")])
        (rb,) = guardian.events("rollback")
        assert rb["restored_step"] > 0 and rb["rollbacks"] == 1
        assert ckpt.latest_checkpoint(root) is not None   # COMMITTED dirs
        # training completed past the poison with finite state
        res = model.train_batch([_batches(1)[0][0]], [_batches(1)[0][1]])
        final_loss = res[0][0] if isinstance(res, tuple) else res[0]
        assert math.isfinite(final_loss)
        for k, v in model.network.state_dict().items():
            assert np.isfinite(np.asarray(v._value)).all(), k

    def test_rollback_restores_bitwise_identical_state(self, tmp_path):
        root = str(tmp_path / "rb")
        model = _reg_model()
        cfg = guardian.GuardianConfig(ckpt_root=root)
        g = guardian.TrainingGuardian(cfg, model)
        model.train_batch([_batches(1)[0][0]], [_batches(1)[0][1]])
        g.save_good(step=1)
        good = {k: np.asarray(v._value).copy()
                for k, v in model.network.state_dict().items()}
        good_opt = [{k: np.asarray(v).copy() for k, v in st.items()}
                    for st in model._stepper.opt_state]
        # diverge, then roll back
        for _ in range(3):
            model.train_batch([_batches(1)[0][0]], [_batches(1)[0][1]])
        g._rollback(step=4)
        for k, v in model.network.state_dict().items():
            np.testing.assert_array_equal(np.asarray(v._value), good[k])
        for st, want in zip(model._stepper.opt_state, good_opt):
            for k, v in st.items():
                np.testing.assert_array_equal(np.asarray(v), want[k])

    def test_rollback_clears_accumulated_grads(self, tmp_path):
        # grads accumulated against pre-rollback weights must be dropped,
        # not averaged into the restored ones
        root = str(tmp_path / "acc")
        model = _reg_model()
        cfg = guardian.GuardianConfig(ckpt_root=root)
        g = guardian.TrainingGuardian(cfg, model)
        x, y = _batches(1)[0]
        model.train_batch([x], [y])
        g.save_good(step=1)
        model.train_batch([x], [y], update=False)    # half-window accum
        assert model._stepper._accum_count == 1
        g._rollback(step=2)
        assert model._stepper._accum_grads is None
        assert model._stepper._accum_count == 0

    def test_check_grads_false_skips_eager_sentinel(self):
        cfg = guardian.GuardianConfig(check_grads=False)
        g = guardian.TrainingGuardian(cfg, model=None)
        g.start()
        try:
            assert guardian._SENTINEL is None    # disabled rung honored
        finally:
            g.stop()

    def test_scaler_plus_sentinel_is_one_sync_per_step(self):
        # unscale_ hands its verdict to the sentinel: the paired
        # optimizer.step must not pay a second fused check + host sync
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        guardian.install_sentinel(
            guardian.NumericSentinel(guardian.GuardianConfig()))
        scaler = amp.GradScaler(init_loss_scaling=2.0)
        for p in opt._parameter_list:
            p._grad = jnp.ones_like(p._value)
        before = guardian.host_sync_count()
        scaler.unscale_(opt)
        scaler.step(opt)
        assert guardian.host_sync_count() - before == 1

    def test_loss_spike_feeds_same_ladder(self, tmp_path):
        # spike-only trip (grads stay finite): detector fires pre-NaN
        model = _reg_model()
        cfg = guardian.GuardianConfig(skip_limit=100, spike_warmup=3,
                                      spike_zscore=4.0, check_grads=False)
        batches = _batches(10, seed=1)
        x_big, y_big = batches[6]
        batches[6] = (x_big * 1e4, y_big * 1e4)    # engineered spike
        model.fit(batches, epochs=1, verbose=0, guardian=cfg)
        assert guardian.events("loss_spike")
        skips = guardian.events("skip_step")
        assert any(s["reason"] == "loss_spike" for s in skips)

    def test_guardian_defaults_off_and_env_opt_in(self, monkeypatch):
        model = _reg_model()
        model.fit(_batches(3), epochs=1, verbose=0)
        assert model._stepper.guard_numerics is False
        assert model._stepper.last_ok is None
        assert not guardian.events()
        monkeypatch.setenv("PADDLE_GUARDIAN", "1")
        cfg = guardian.GuardianConfig.from_env()
        assert cfg is not None and cfg.check_grads

    def test_strategy_carries_guardian_knobs(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        s = DistributedStrategy()
        assert s.guardian is False
        s.guardian = True
        s.guardian_configs["skip_limit"] = 7
        cfg = guardian.GuardianConfig.from_strategy(s)
        assert cfg.skip_limit == 7 and cfg.loss_spike


# -- data-parallel fit under guardian (two-rank mesh, GSPMD) --------------

class TestGuardianUnderDataParallel:
    def test_dp_fit_skips_in_lockstep(self, tmp_path):
        # GSPMD DP: grads are global arrays, so the fused verdict is
        # globally consistent by construction — the run must complete
        # with finite replicated params after a poisoned batch
        paddle.seed(11)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        dp = paddle.DataParallel(net)
        model = paddle.Model(dp)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters())
        model.prepare(opt, nn.MSELoss())
        rng = np.random.RandomState(0)
        batches = [(rng.randn(16, 16).astype("f4"),
                    rng.randn(16, 4).astype("f4")) for _ in range(8)]
        cfg = guardian.GuardianConfig(skip_limit=5, loss_spike=False)
        model.fit(batches, epochs=1, verbose=0, guardian=cfg,
                  callbacks=[_ArmAt(2, "guardian.poison_batch", "skip*1")])
        assert len(guardian.events("skip_step")) == 1
        p = net.parameters()[0]
        assert p._value.sharding.is_fully_replicated
        assert np.isfinite(np.asarray(p._value)).all()


# -- collective watchdog --------------------------------------------------

class TestCollectiveWatchdog:
    def test_new_group_timeout_is_stored_not_dropped(self):
        g = collective.new_group(timeout=2.5)
        assert g.timeout == 2.5
        import datetime
        g2 = collective.new_group(
            timeout=datetime.timedelta(seconds=3))
        assert g2.timeout == 3.0

    def test_barrier_timeout_raises_and_dumps_last_ops(self):
        guardian.track_collectives(True)
        t = paddle.to_tensor(np.ones(2, dtype="f4"))
        collective.all_reduce(t)                     # lands in the ring
        failpoints.set_failpoint("collective.barrier", "delay:1.5*1")
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="barrier"):
            collective.barrier(timeout=0.2)
        assert time.monotonic() - t0 < 1.2           # pre-deadline abort
        (ev,) = guardian.events("watchdog_timeout")
        assert ev["op"] == "barrier" and ev["timeout"] == 0.2
        assert any(o["op"] == "all_reduce" for o in ev["last_ops"])

    def test_barrier_group_timeout_honored(self):
        g = collective.new_group(timeout=0.2)
        failpoints.set_failpoint("collective.barrier", "delay:1.5*1")
        with pytest.raises(TimeoutError):
            collective.barrier(group=g)

    def test_barrier_unmonitored_and_fast_paths_ok(self):
        collective.barrier()                          # no timeout: no-op
        collective.barrier(timeout=5.0)               # fast body: passes
        assert not guardian.events("watchdog_timeout")

    def test_run_with_deadline_propagates_body_error(self):
        with pytest.raises(KeyError):
            guardian.run_with_deadline(
                lambda: (_ for _ in ()).throw(KeyError("x")),
                timeout=1.0, op="test")


# -- check_numerics routing -----------------------------------------------

class TestCheckNumerics:
    def test_clean_tensor_passes_silently(self):
        t = paddle.to_tensor(np.ones(4, dtype="f4"))
        amp.debugging.check_numerics(t, "relu", "out")
        assert not guardian.events("check_numerics")

    def test_nan_tensor_raises_through_guardian_log(self):
        t = paddle.to_tensor(np.asarray([1.0, float("nan")], dtype="f4"))
        with pytest.raises(FloatingPointError, match="1 NaN"):
            amp.debugging.check_numerics(t, "log", "x")
        (ev,) = guardian.events("check_numerics")
        assert ev["op_type"] == "log" and ev["nan_count"] == 1
        assert ev["forced"] is False

    def test_failpoint_forces_trip_on_clean_tensor(self):
        failpoints.set_failpoint("guardian.check_numerics", "skip*1")
        t = paddle.to_tensor(np.ones(4, dtype="f4"))
        with pytest.raises(FloatingPointError, match="forced"):
            amp.debugging.check_numerics(t, "matmul", "y")
        (ev,) = guardian.events("check_numerics")
        assert ev["forced"] is True
        amp.debugging.check_numerics(t, "matmul", "y")   # drained: clean

    def test_finite_float64_above_f32_max_passes(self):
        # native numpy dtypes are never cast through f32 — a finite f64
        # of 1e300 must not be misreported as Inf
        amp.debugging.check_numerics(np.asarray([1e300, 2.0]), "op", "v")
        assert not guardian.events("check_numerics")
