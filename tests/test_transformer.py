"""Transformer + attention tests (reference:
test/legacy_test/test_multihead_attention* / test_transformer_api.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _np_attention(q, k, v, causal=False):
    B, S, H, D = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask, s, -1e30)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v).astype("f4")


def test_sdpa_matches_numpy():
    rng = np.random.RandomState(0)
    q = rng.randn(2, 8, 2, 4).astype("f4")
    k = rng.randn(2, 8, 2, 4).astype("f4")
    v = rng.randn(2, 8, 2, 4).astype("f4")
    out = F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
    np.testing.assert_allclose(out.numpy(), _np_attention(q, k, v),
                               rtol=1e-4, atol=1e-5)


def test_sdpa_causal_and_grad():
    rng = np.random.RandomState(1)
    q = paddle.to_tensor(rng.randn(1, 6, 2, 4).astype("f4"),
                         stop_gradient=False)
    k = paddle.to_tensor(rng.randn(1, 6, 2, 4).astype("f4"),
                         stop_gradient=False)
    v = paddle.to_tensor(rng.randn(1, 6, 2, 4).astype("f4"),
                         stop_gradient=False)
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    ref = _np_attention(q.numpy(), k.numpy(), v.numpy(), causal=True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
    out.sum().backward()
    assert q.grad is not None and np.isfinite(q.grad.numpy()).all()
    # causal: grad of q at pos 0 depends only on k/v[0]
    assert k.grad is not None


def test_sdpa_gqa():
    rng = np.random.RandomState(2)
    q = rng.randn(2, 4, 8, 4).astype("f4")
    k = rng.randn(2, 4, 2, 4).astype("f4")  # 2 kv heads, 8 q heads
    v = rng.randn(2, 4, 2, 4).astype("f4")
    out = F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
    kr = np.repeat(k, 4, axis=2)
    vr = np.repeat(v, 4, axis=2)
    np.testing.assert_allclose(out.numpy(), _np_attention(q, kr, vr),
                               rtol=1e-4, atol=1e-5)


def test_mha_cache_decoding():
    paddle.seed(0)
    mha = nn.MultiHeadAttention(16, 4)
    mha.eval()
    x = paddle.randn([1, 5, 16])
    causal = nn.Transformer.generate_square_subsequent_mask(5)
    full = mha(x, x, x, causal)
    # incremental: feed tokens one at a time with Cache
    cache = mha.gen_cache(paddle.randn([1, 0, 16]))
    outs = []
    for t in range(5):
        step = x[:, t:t + 1, :]
        o, cache = mha(step, step, step, None, cache)
        outs.append(o.numpy())
    inc = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(inc, full.numpy(), rtol=1e-4, atol=1e-5)


def test_encoder_decoder_shapes_and_grad():
    paddle.seed(0)
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32)
    src = paddle.randn([2, 7, 16])
    tgt = paddle.randn([2, 5, 16])
    out = model(src, tgt)
    assert out.shape == [2, 5, 16]
    out.mean().backward()
    grads = [p.grad for p in model.parameters() if p.grad is not None]
    assert len(grads) > 0


def test_generate_square_subsequent_mask():
    m = nn.Transformer.generate_square_subsequent_mask(4).numpy()
    assert m[0, 1] < -1e29 and m[1, 0] == 0 and m[3, 3] == 0
