"""Extended vision transforms, distribution transforms, VisualDL callback.

Reference analogues: test/legacy_test/test_transforms.py,
test/distribution/test_distribution_transform.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import transforms as T
from paddle_tpu.distribution import (
    Normal, TransformedDistribution, ExpTransform, AffineTransform,
    SigmoidTransform, TanhTransform, ChainTransform, StickBreakingTransform,
    PowerTransform, ReshapeTransform, IndependentTransform)


def _img(h=32, w=32, c=3, seed=0):
    return (np.random.RandomState(seed).rand(h, w, c) * 255).astype(
        "float32")


class TestVisionTransforms:
    def test_adjusts_match_identity_at_factor_one(self):
        img = _img()
        np.testing.assert_allclose(T.adjust_brightness(img, 1.0), img,
                                   atol=1e-3)
        np.testing.assert_allclose(T.adjust_contrast(img, 1.0), img,
                                   atol=1e-3)
        np.testing.assert_allclose(T.adjust_saturation(img, 1.0), img,
                                   atol=1e-3)
        np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=0.5)

    def test_hue_full_rotation_identity(self):
        img = _img(8, 8)
        out = T.adjust_hue(T.adjust_hue(img, 0.5), 0.5)
        np.testing.assert_allclose(out, img, atol=0.5)

    def test_grayscale(self):
        img = _img()
        g1 = T.to_grayscale(img)
        assert g1.shape == (32, 32, 1)
        g3 = T.Grayscale(3)(img)
        assert g3.shape == (32, 32, 3)
        np.testing.assert_allclose(g3[..., 0], g3[..., 1])

    def test_center_crop_and_crop(self):
        img = _img(10, 10, 1)
        cc = T.center_crop(img, 4)
        np.testing.assert_allclose(cc, img[3:7, 3:7])
        c = T.crop(img, 1, 2, 3, 4)
        np.testing.assert_allclose(c, img[1:4, 2:6])

    def test_random_resized_crop(self):
        out = T.RandomResizedCrop(16)(_img())
        assert out.shape[:2] == (16, 16)

    def test_random_erasing(self):
        img = np.ones((8, 8, 1), "float32")
        out = T.RandomErasing(prob=1.0, value=0)(img)
        assert out.min() == 0.0 and out.max() == 1.0

    def test_color_jitter_runs(self):
        out = T.ColorJitter(0.2, 0.2, 0.2, 0.1)(_img())
        assert out.shape == (32, 32, 3)

    def test_erase(self):
        img = np.zeros((6, 6, 1), "float32")
        out = T.erase(img, 1, 2, 2, 3, 7.0)
        assert out[1:3, 2:5].min() == 7.0
        assert out[0].max() == 0.0

    def test_compose_pipeline(self):
        pipe = T.Compose([T.RandomResizedCrop(16), T.ColorJitter(0.1),
                          T.ToTensor()])
        out = pipe(_img())
        assert tuple(out.shape) == (3, 16, 16)


class TestDistributionTransforms:
    def test_exp_lognormal_parity(self):
        from scipy.stats import lognorm
        base = Normal(loc=paddle.to_tensor(0.0), scale=paddle.to_tensor(1.0))
        d = TransformedDistribution(base, [ExpTransform()])
        y = np.array([0.5, 1.0, 2.0], "float32")
        got = d.log_prob(paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(got, lognorm.logpdf(y, 1.0), rtol=1e-5)

    def test_affine_forward_inverse(self):
        t = AffineTransform(paddle.to_tensor(2.0), paddle.to_tensor(3.0))
        x = paddle.to_tensor(np.array([1.0, -1.0], "float32"))
        y = t.forward(x)
        np.testing.assert_allclose(y.numpy(), [5.0, -1.0])
        back = t.inverse(y)
        np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)
        ldj = t.forward_log_det_jacobian(x)
        np.testing.assert_allclose(ldj.numpy(), np.log(3.0) * np.ones(2),
                                   rtol=1e-6)

    @pytest.mark.parametrize("t", [SigmoidTransform(), TanhTransform(),
                                   ExpTransform(), PowerTransform(2.0)])
    def test_fldj_matches_autodiff(self, t):
        import jax
        x = np.array([0.3, 0.9, 1.7], "float32")
        got = t.forward_log_det_jacobian(paddle.to_tensor(x)).numpy()
        ref = np.log(np.abs(jax.vmap(jax.grad(
            lambda v: t._forward(v)))(x)))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_chain(self):
        t = ChainTransform([AffineTransform(0.0, 2.0), ExpTransform()])
        x = paddle.to_tensor(np.array([0.1, 0.5], "float32"))
        y = t.forward(x)
        np.testing.assert_allclose(y.numpy(), np.exp(2 * x.numpy()),
                                   rtol=1e-5)
        np.testing.assert_allclose(t.inverse(y).numpy(), x.numpy(),
                                   rtol=1e-5)

    def test_stickbreaking(self):
        t = StickBreakingTransform()
        x = paddle.to_tensor(np.array([0.2, -0.4, 0.1], "float32"))
        y = t.forward(x)
        assert y.shape == [4]
        np.testing.assert_allclose(y.numpy().sum(), 1.0, rtol=1e-5)
        back = t.inverse(y)
        np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-4,
                                   atol=1e-5)
        # fldj vs autodiff jacobian determinant of first K components
        import jax
        import jax.numpy as jnp
        J = jax.jacfwd(lambda v: t._forward(v)[:-1])(x.numpy())
        ref = np.log(np.abs(np.linalg.det(np.asarray(J))))
        got = float(t.forward_log_det_jacobian(x).numpy())
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_chain_log_prob(self):
        # composite transforms must support inverse_log_det_jacobian
        d = TransformedDistribution(
            Normal(paddle.to_tensor(0.0), paddle.to_tensor(1.0)),
            [ChainTransform([ExpTransform(), AffineTransform(1.0, 2.0)])])
        lp = d.log_prob(paddle.to_tensor(np.array([3.0], "float32")))
        x = np.log((3.0 - 1) / 2)
        ref = -0.5 * np.log(2 * np.pi) - x ** 2 / 2 - np.log(3.0 - 1.0)
        np.testing.assert_allclose(lp.numpy(), [ref], rtol=1e-5)

    def test_uint8_near_black_brightness(self):
        img = np.zeros((4, 4, 3), np.uint8)
        img[0, 0] = 1
        out = T.adjust_brightness(img, 100.0)
        assert out[0, 0, 0] == 100   # not clipped to a [0,1] range
        assert out.dtype == np.uint8  # dtype evidence survives chaining

    def test_uint8_chained_jitter_keeps_scale(self):
        img = np.zeros((4, 4, 3), np.uint8)
        img[0, 0] = 1
        out = T.adjust_contrast(T.adjust_brightness(img, 100.0), 1.0)
        assert out.dtype == np.uint8
        assert out[0, 0, 0] >= 99    # second op must not clip to [0,1]

    def test_rotate_arbitrary_angle(self):
        img = np.zeros((11, 11, 1), "float32")
        img[5, 8] = 1.0   # point 3 px right of center
        out = T.rotate(img, 45.0)
        # destination of (dy=0,dx=3) under +45° ≈ (dy≈-2.1, dx≈2.1)
        ys, xs = np.nonzero(out[..., 0])
        assert len(ys) >= 1
        assert abs(int(ys[0]) - 3) <= 1 and abs(int(xs[0]) - 7) <= 1
        # 90° multiples stay exact
        np.testing.assert_array_equal(T.rotate(img, 90),
                                      np.rot90(img, 1, axes=(0, 1)))

    def test_reshape_independent(self):
        t = ReshapeTransform((4,), (2, 2))
        x = paddle.to_tensor(np.arange(4, dtype="float32"))
        y = t.forward(x)
        assert y.shape == [2, 2]
        it = IndependentTransform(AffineTransform(0.0, 2.0), 1)
        x2 = paddle.to_tensor(np.ones((3, 4), "float32"))
        ldj = it.forward_log_det_jacobian(x2)
        np.testing.assert_allclose(ldj.numpy(),
                                   np.full(3, 4 * np.log(2.0)), rtol=1e-5)


class TestVisualDL:
    def test_scalar_logging(self, tmp_path):
        import json
        from paddle_tpu.hapi.callbacks import VisualDL
        cb = VisualDL(log_dir=str(tmp_path))
        cb.on_train_batch_end(0, {"loss": 1.5, "acc": 0.5})
        cb.on_train_batch_end(1, {"loss": 1.2, "acc": 0.6})
        cb.on_train_end()
        lines = [json.loads(l) for l in
                 open(tmp_path / "train.jsonl").read().splitlines()]
        assert lines[0]["loss"] == 1.5 and lines[1]["step"] == 1
