"""Varlen (unpadded) flash attention — segment-masked Pallas kernel vs a
padded-dense golden (reference contract:
paddle.nn.functional.flash_attention.flash_attn_unpadded over cu_seqlens
prefix sums; cutlass varlen_fwd/varlen_bwd).  Runs in interpret mode on
CPU like the other Pallas suites."""
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops.pallas.flash_attention_varlen import (
    flash_attn_unpadded as raw_unpadded, _segments_from_cu)

LENS = [100, 37, 256, 119]   # ragged pack, total = 512


def _pack(rng, lens, H, D):
    total = sum(lens)
    q = rng.randn(total, H, D).astype("float32")
    k = rng.randn(total, H, D).astype("float32")
    v = rng.randn(total, H, D).astype("float32")
    cu = np.concatenate([[0], np.cumsum(lens)]).astype("int32")
    return q, k, v, cu


def _golden(q, k, v, cu, causal):
    """Per-sequence dense attention on the packed slices."""
    out = np.zeros_like(q)
    H, D = q.shape[1], q.shape[2]
    for s in range(len(cu) - 1):
        lo, hi = cu[s], cu[s + 1]
        qs, ks, vs = q[lo:hi], k[lo:hi], v[lo:hi]      # (L, H, D)
        s_ = np.einsum("qhd,khd->hqk", qs, ks) / math.sqrt(D)
        if causal:
            L = hi - lo
            mask = np.tril(np.ones((L, L), bool))
            s_ = np.where(mask[None], s_, -1e30)
        p = jax.nn.softmax(jnp.asarray(s_), -1)
        out[lo:hi] = np.einsum("hqk,khd->qhd", np.asarray(p), vs)
    return out


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_fwd_matches_per_sequence_dense(causal):
    rng = np.random.RandomState(0)
    H, D = 4, 64
    q, k, v, cu = _pack(rng, LENS, H, D)
    out, _ = raw_unpadded(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          cu, cu, max(LENS), max(LENS), causal=causal,
                          interpret=True)
    ref = _golden(q, k, v, cu, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_bwd_matches_dense_vjp(causal):
    rng = np.random.RandomState(1)
    H, D = 2, 64
    q, k, v, cu = _pack(rng, [61, 195], H, D)
    g = rng.randn(*q.shape).astype("float32")
    seg = np.asarray(_segments_from_cu(cu, q.shape[0]))

    def dense(qq, kk, vv):
        s = jnp.einsum("qhd,khd->hqk", qq, kk) / math.sqrt(D)
        live = seg[:, None] == seg[None, :]
        if causal:
            pos = np.arange(q.shape[0])
            live = live & (pos[:, None] >= pos[None, :])
        s = jnp.where(jnp.asarray(live)[None], s, -1e30)
        return jnp.einsum("hqk,khd->qhd", jax.nn.softmax(s, -1), vv)

    def kernel_fn(qq, kk, vv):
        return raw_unpadded(qq, kk, vv, cu, cu, 195, 195, causal=causal,
                            interpret=True)[0]

    rq, rk, rv = jax.vjp(dense, jnp.asarray(q), jnp.asarray(k),
                         jnp.asarray(v))[1](jnp.asarray(g))
    dq, dk, dv = jax.vjp(kernel_fn, jnp.asarray(q), jnp.asarray(k),
                         jnp.asarray(v))[1](jnp.asarray(g))
    for got, want, nm in [(dq, rq, "dq"), (dk, rk, "dk"), (dv, rv, "dv")]:
        rel = float(jnp.abs(got - want).max() / (jnp.abs(want).max() + 1e-9))
        assert rel < 5e-3, f"{nm}: {rel}"


def test_varlen_isolation_across_sequences():
    """Changing sequence 0's keys must not change sequence 1's output."""
    rng = np.random.RandomState(2)
    H, D = 2, 64
    q, k, v, cu = _pack(rng, [128, 128], H, D)
    out1, _ = raw_unpadded(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           cu, cu, 128, 128, causal=True, interpret=True)
    k2 = k.copy()
    k2[:128] += 100.0                                  # perturb seq 0 only
    out2, _ = raw_unpadded(jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v),
                           cu, cu, 128, 128, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out1)[128:],
                               np.asarray(out2)[128:], rtol=1e-6)
    assert not np.allclose(np.asarray(out1)[:128], np.asarray(out2)[:128])


def test_public_api_tensor_grads_flow():
    """nn.functional entry: Tensor in/out, grads through the tape."""
    rng = np.random.RandomState(3)
    H, D = 2, 64
    qn, kn, vn, cu = _pack(rng, [70, 58], H, D)
    q = paddle.to_tensor(qn, stop_gradient=False)
    k = paddle.to_tensor(kn, stop_gradient=False)
    v = paddle.to_tensor(vn, stop_gradient=False)
    out, _ = F.flash_attn_unpadded(q, k, v, cu, cu, 70, 70, causal=True)
    out.sum().backward()
    assert q.grad is not None and k.grad is not None and v.grad is not None
    assert np.isfinite(q.grad.numpy()).all()


def test_packed_equals_padded_gpt_loss():
    """VERDICT r2 #2 done-criterion: a packed-sequence batch trains with
    the same loss as the padded equivalent.  Two sequences of different
    lengths attend identically whether packed (varlen kernel) or padded
    into separate batch rows (dense attention)."""
    rng = np.random.RandomState(4)
    H, D = 2, 64
    lens = [96, 160]
    q, k, v, cu = _pack(rng, lens, H, D)
    packed, _ = raw_unpadded(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), cu, cu, 160, 160, causal=True,
                             interpret=True)
    # padded equivalent: each sequence its own (S, H, D) run
    for i, L in enumerate(lens):
        lo, hi = int(cu[i]), int(cu[i + 1])
        ref = _golden(q[lo:hi], k[lo:hi], v[lo:hi],
                      np.asarray([0, L], "i4"), True)
        np.testing.assert_allclose(np.asarray(packed)[lo:hi], ref,
                                   rtol=2e-3, atol=2e-3)


def test_varlen_cross_pack_different_cu():
    """cu_seqlens_q != cu_seqlens_k (cross-attention pack): k must be
    masked by ITS OWN prefix sums (review r3: seg ids were built from
    cu_q only and mis-masked k)."""
    rng = np.random.RandomState(5)
    H, D = 2, 64
    total = 256
    q = rng.randn(total, H, D).astype("f4")
    k = rng.randn(total, H, D).astype("f4")
    v = rng.randn(total, H, D).astype("f4")
    cu_q = np.asarray([0, 100, 256], "i4")
    cu_k = np.asarray([0, 160, 256], "i4")
    out, _ = raw_unpadded(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          cu_q, cu_k, 156, 160, causal=False,
                          interpret=True)
    # golden: q seq i attends exactly k's slice of segment i
    sq = np.asarray(_segments_from_cu(cu_q, total))
    sk = np.asarray(_segments_from_cu(cu_k, total))
    s = np.einsum("qhd,khd->hqk", q, k) / math.sqrt(D)
    live = sq[:, None] == sk[None, :]
    s = np.where(live[None], s, -1e30)
    p = np.asarray(jax.nn.softmax(jnp.asarray(s), -1))
    ref = np.einsum("hqk,khd->qhd", p, v)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_varlen_causal_rejects_mismatched_cu():
    rng = np.random.RandomState(6)
    q = rng.randn(128, 2, 64).astype("f4")
    with pytest.raises(ValueError, match="cu_seqlens_q"):
        raw_unpadded(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q),
                     np.asarray([0, 64, 128], "i4"),
                     np.asarray([0, 100, 128], "i4"), 64, 100,
                     causal=True, interpret=True)


def test_varlen_dense_dropout_applied():
    """dropout>0 on the dense fallback actually drops (review r3: the
    parameter was silently ignored)."""
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(64, 2, 32), jnp.float32)
    cu = np.asarray([0, 64], "i4")
    key = jax.random.key(0)
    out_d, _ = raw_unpadded(q, q, q, cu, cu, 64, 64, dropout=0.5,
                            causal=False, dropout_key=key)
    out_0, _ = raw_unpadded(q, q, q, cu, cu, 64, 64, dropout=0.0,
                            causal=False, interpret=True)
    assert not np.allclose(np.asarray(out_d), np.asarray(out_0))
    with pytest.raises(ValueError, match="dropout_key"):
        raw_unpadded(q, q, q, cu, cu, 64, 64, dropout=0.5, causal=False)


# -- round 4: streaming two-pass bwd, dead rows, mismatched totals ----------

def _packed_hTd(x, Tp):
    x = jnp.moveaxis(jnp.asarray(x), 1, 0)
    grow = Tp - x.shape[1]
    return jnp.pad(x, ((0, 0), (0, grow), (0, 0))) if grow else x


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_streaming_tier_matches_resident(causal):
    """The streaming (nothing-full-T-resident) fwd/bwd kernels must
    agree with the resident one-pass tier on the same pack (VERDICT
    r3 #3 — the >8k-token path)."""
    from paddle_tpu.ops.pallas.flash_attention_varlen import (
        _varlen_fwd, _varlen_fwd_stream, _varlen_bwd, _varlen_bwd_stream)
    rng = np.random.RandomState(4)
    H, D = 2, 64
    q, k, v, cu = _pack(rng, [200, 312], H, D)       # T = 512
    T = q.shape[0]
    seg = _segments_from_cu(cu, T)
    qh, kh, vh = (_packed_hTd(t, T) for t in (q, k, v))
    o, lse = _varlen_fwd(qh, kh, vh, seg, seg, causal, block_q=256,
                         block_k=256, interpret=True)
    o2, lse2 = _varlen_fwd_stream(qh, kh, vh, seg, seg, causal,
                                  block_q=256, block_k=256,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(o),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse2), np.asarray(lse),
                               rtol=1e-4, atol=1e-5)
    do = jnp.asarray(rng.randn(H, T, D).astype("f4"))
    one = _varlen_bwd(qh, kh, vh, o, lse, do, seg, seg, causal,
                      block_q=256, block_k=256, interpret=True)
    two = _varlen_bwd_stream(qh, kh, vh, o, lse, do, seg, seg, causal,
                             block_q=256, block_k=256, interpret=True)
    for a, b, nm in zip(one, two, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=nm)


def test_varlen_dead_q_rows_emit_zeros():
    """A q segment with zero live keys (ADVICE r3): output and grads are
    exactly 0, not the mean of masked v rows."""
    rng = np.random.RandomState(5)
    H, D = 2, 64
    total_q = 256
    q = rng.randn(total_q, H, D).astype("f4")
    k = rng.randn(128, H, D).astype("f4")
    v = rng.randn(128, H, D).astype("f4")
    cu_q = np.asarray([0, 128, 256], "i4")
    cu_k = np.asarray([0, 128, 128], "i4")   # segment 1: zero keys

    def f(qq, kk, vv):
        return raw_unpadded(qq, kk, vv, cu_q, cu_k, 128, 128,
                            causal=False, interpret=True)[0]

    out, vjp = jax.vjp(f, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out)[128:], 0.0)
    assert np.abs(np.asarray(out)[:128]).max() > 0
    g = jnp.asarray(rng.randn(*out.shape).astype("f4"))
    dq, dk, dv = vjp(g)
    np.testing.assert_allclose(np.asarray(dq)[128:], 0.0)
    # seq-0 keys must receive no gradient from the dead seq-1 rows:
    # perturbing g on dead rows changes nothing
    g2 = g.at[128:].add(100.0)
    dq2, dk2, dv2 = vjp(g2)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv2), atol=1e-6)


def test_varlen_mismatched_totals_cross_attention():
    """total_q != total_k packs are padded to a common total (ADVICE r3)
    and match the per-sequence dense golden."""
    rng = np.random.RandomState(6)
    H, D = 2, 64
    q_lens, k_lens = [40, 72], [64, 64]
    tq, tk = sum(q_lens), sum(k_lens)
    q = rng.randn(tq, H, D).astype("f4")
    k = rng.randn(tk, H, D).astype("f4")
    v = rng.randn(tk, H, D).astype("f4")
    cu_q = np.concatenate([[0], np.cumsum(q_lens)]).astype("i4")
    cu_k = np.concatenate([[0], np.cumsum(k_lens)]).astype("i4")
    out, _ = raw_unpadded(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          cu_q, cu_k, max(q_lens), max(k_lens),
                          causal=False, interpret=True)
    ref = np.zeros_like(q)
    for s in range(2):
        qs = q[cu_q[s]:cu_q[s + 1]]
        ks = k[cu_k[s]:cu_k[s + 1]]
        vs = v[cu_k[s]:cu_k[s + 1]]
        s_ = np.einsum("qhd,khd->hqk", qs, ks) / math.sqrt(D)
        p = np.asarray(jax.nn.softmax(jnp.asarray(s_), -1))
        ref[cu_q[s]:cu_q[s + 1]] = np.einsum("hqk,khd->qhd", p, vs)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_varlen_return_softmax_debug_mode():
    rng = np.random.RandomState(7)
    H, D = 2, 64
    q, k, v, cu = _pack(rng, [30, 34], H, D)
    out, p = raw_unpadded(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          cu, cu, 34, 34, causal=False,
                          return_softmax=True, interpret=True)
    assert p is not None and p.shape == (H, 64, 64)
    rows = np.asarray(p).sum(-1)
    np.testing.assert_allclose(rows, 1.0, rtol=1e-5)
    # cross-segment probabilities are zero
    assert float(np.abs(np.asarray(p)[:, :30, 30:]).max()) == 0.0
