"""Model-zoo smoke tests: construction, forward shape, and (ResNet-50)
backward. Mirrors the reference's test_vision_models.py pattern of
per-arch shape checks on small inputs."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _check(net, size=64, num_classes=10):
    x = paddle.randn([2, 3, size, size])
    out = net(x)
    assert out.shape == [2, num_classes]
    return out


def test_resnet18():
    _check(models.resnet18(num_classes=10))


def test_resnet50():
    net = models.resnet50(num_classes=10)
    out = _check(net)
    loss = out.sum()
    loss.backward()
    g = net.conv1.weight.grad
    assert g is not None and g.shape == net.conv1.weight.shape
    assert np.isfinite(g.numpy()).all()


def test_resnext_and_wide():
    _check(models.resnext50_32x4d(num_classes=10))
    _check(models.wide_resnet50_2(num_classes=10))


def test_vgg11():
    _check(models.vgg11(num_classes=10))


def test_alexnet():
    x = paddle.randn([2, 3, 224, 224])
    assert models.alexnet(num_classes=10)(x).shape == [2, 10]


def test_mobilenets():
    _check(models.mobilenet_v1(num_classes=10))
    _check(models.mobilenet_v2(num_classes=10))
    _check(models.mobilenet_v3_small(num_classes=10))
    _check(models.mobilenet_v3_large(num_classes=10))


def test_shufflenet():
    _check(models.shufflenet_v2_x0_25(num_classes=10))


def test_squeezenet():
    x = paddle.randn([2, 3, 64, 64])
    assert models.squeezenet1_1(num_classes=10)(x).shape == [2, 10]


def test_densenet():
    _check(models.densenet121(num_classes=10))


def test_googlenet():
    _check(models.googlenet(num_classes=10))


def test_inception_v3():
    x = paddle.randn([2, 3, 128, 128])
    assert models.inception_v3(num_classes=10)(x).shape == [2, 10]


def test_eval_mode_deterministic():
    net = models.resnet18(num_classes=10)
    net.eval()
    x = paddle.randn([1, 3, 64, 64])
    a, b = net(x).numpy(), net(x).numpy()
    np.testing.assert_allclose(a, b)


def test_state_dict_roundtrip(tmp_path):
    net = models.resnet18(num_classes=10)
    sd = net.state_dict()
    # BN running stats must be present
    assert any("_mean" in k or "mean" in k for k in sd)
    net2 = models.resnet18(num_classes=10)
    net2.set_state_dict(sd)
    net.eval(), net2.eval()
    x = paddle.randn([1, 3, 64, 64])
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)
