"""deform_conv2d / roi_pool / psroi_pool (reference:
test/legacy_test/test_deform_conv2d.py, test_roi_pool_op.py)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.vision.ops import (deform_conv2d, roi_pool, psroi_pool,
                                   DeformConv2D)


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype("float32")
        w = rng.randn(4, 3, 3, 3).astype("float32")
        off = np.zeros((2, 2 * 9, 6, 6), "float32")
        got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                            paddle.to_tensor(w)).numpy()
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-4,
                                   atol=1e-4)

    def test_integer_shift_offset(self):
        # offset (+1,+1) on a 1x1 kernel == shifted image sample
        x = np.arange(25, dtype="float32").reshape(1, 1, 5, 5)
        w = np.ones((1, 1, 1, 1), "float32")
        off = np.ones((1, 2, 5, 5), "float32")
        got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                            paddle.to_tensor(w)).numpy()
        # sample at (i+1, j+1), zero outside
        ref = np.zeros((1, 1, 5, 5), "float32")
        ref[0, 0, :4, :4] = x[0, 0, 1:, 1:]
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_mask_v2_and_grads(self):
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(1, 2, 6, 6).astype("float32"))
        w = paddle.to_tensor(rng.randn(3, 2, 3, 3).astype("float32"))
        off = paddle.to_tensor(
            0.1 * rng.randn(1, 18, 4, 4).astype("float32"))
        m = paddle.to_tensor(rng.rand(1, 9, 4, 4).astype("float32"))
        for t in (x, w, off):
            t.stop_gradient = False
        out = deform_conv2d(x, off, w, mask=m)
        assert list(out.shape) == [1, 3, 4, 4]
        paddle.sum(out * out).backward()
        assert x.grad is not None and w.grad is not None \
            and off.grad is not None

    def test_layer(self):
        layer = DeformConv2D(2, 4, 3, padding=1)
        x = paddle.to_tensor(np.random.RandomState(2)
                             .randn(1, 2, 5, 5).astype("float32"))
        off = paddle.to_tensor(np.zeros((1, 18, 5, 5), "float32"))
        out = layer(x, off)
        assert list(out.shape) == [1, 4, 5, 5]


class TestRoiPool:
    def test_roi_pool_values(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        boxes = np.array([[0.0, 0.0, 3.0, 3.0]], "float32")
        got = roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                       paddle.to_tensor(np.array([1], "int32")),
                       output_size=2).numpy()
        # 2x2 max pooling over the full 4x4 box
        ref = np.array([[[[5.0, 7.0], [13.0, 15.0]]]], "float32")
        np.testing.assert_allclose(got, ref)

    def test_psroi_pool_shape_and_mean(self):
        # C = out_c * ph * pw = 2*2*2 = 8
        x = np.ones((1, 8, 4, 4), "float32")
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], "float32")
        got = psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], "int32")),
                         output_size=2).numpy()
        assert got.shape == (1, 2, 2, 2)
        np.testing.assert_allclose(got, np.ones((1, 2, 2, 2)), rtol=1e-6)

    def test_psroi_pool_channel_major_order(self):
        # channel k filled with value k: out channel c bin (i,j) must read
        # input channel c*ph*pw + i*pw + j (R-FCN channel-major layout)
        C, ph, pw = 8, 2, 2
        x = np.zeros((1, C, 4, 4), "float32")
        for k in range(C):
            x[0, k] = k
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], "float32")
        got = psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], "int32")),
                         output_size=2).numpy()
        ref = np.zeros((1, 2, ph, pw), "float32")
        for c in range(2):
            for i in range(ph):
                for j in range(pw):
                    ref[0, c, i, j] = c * ph * pw + i * pw + j
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_batched_input_raises(self):
        x = np.ones((2, 8, 4, 4), "float32")
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], "float32")
        with pytest.raises(NotImplementedError):
            psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                       paddle.to_tensor(np.array([1, 0], "int32")), 2)
        with pytest.raises(NotImplementedError):
            roi_pool(paddle.to_tensor(np.ones((2, 1, 4, 4), "float32")),
                     paddle.to_tensor(boxes),
                     paddle.to_tensor(np.array([1, 0], "int32")), 2)
