"""deform_conv2d / roi_pool / psroi_pool (reference:
test/legacy_test/test_deform_conv2d.py, test_roi_pool_op.py)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.vision.ops import (deform_conv2d, roi_pool, psroi_pool,
                                   DeformConv2D, box_coder, yolo_box)


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype("float32")
        w = rng.randn(4, 3, 3, 3).astype("float32")
        off = np.zeros((2, 2 * 9, 6, 6), "float32")
        got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                            paddle.to_tensor(w)).numpy()
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-4,
                                   atol=1e-4)

    def test_integer_shift_offset(self):
        # offset (+1,+1) on a 1x1 kernel == shifted image sample
        x = np.arange(25, dtype="float32").reshape(1, 1, 5, 5)
        w = np.ones((1, 1, 1, 1), "float32")
        off = np.ones((1, 2, 5, 5), "float32")
        got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                            paddle.to_tensor(w)).numpy()
        # sample at (i+1, j+1), zero outside
        ref = np.zeros((1, 1, 5, 5), "float32")
        ref[0, 0, :4, :4] = x[0, 0, 1:, 1:]
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_mask_v2_and_grads(self):
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(1, 2, 6, 6).astype("float32"))
        w = paddle.to_tensor(rng.randn(3, 2, 3, 3).astype("float32"))
        off = paddle.to_tensor(
            0.1 * rng.randn(1, 18, 4, 4).astype("float32"))
        m = paddle.to_tensor(rng.rand(1, 9, 4, 4).astype("float32"))
        for t in (x, w, off):
            t.stop_gradient = False
        out = deform_conv2d(x, off, w, mask=m)
        assert list(out.shape) == [1, 3, 4, 4]
        paddle.sum(out * out).backward()
        assert x.grad is not None and w.grad is not None \
            and off.grad is not None

    def test_layer(self):
        layer = DeformConv2D(2, 4, 3, padding=1)
        x = paddle.to_tensor(np.random.RandomState(2)
                             .randn(1, 2, 5, 5).astype("float32"))
        off = paddle.to_tensor(np.zeros((1, 18, 5, 5), "float32"))
        out = layer(x, off)
        assert list(out.shape) == [1, 4, 5, 5]

    def test_groups_zero_offset_equals_grouped_conv(self):
        rng = np.random.RandomState(3)
        x = rng.randn(2, 4, 8, 8).astype("float32")
        w = rng.randn(6, 2, 3, 3).astype("float32")  # groups=2: Ci=4/2
        off = np.zeros((2, 2 * 9, 6, 6), "float32")
        got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                            paddle.to_tensor(w), groups=2).numpy()
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=2)
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-4,
                                   atol=1e-4)

    def test_deformable_groups_shift_per_block(self):
        # dg=2 with a 1x1 kernel: block 0 shifts (+1,+1), block 1 stays.
        x = np.stack([np.arange(25, dtype="float32").reshape(5, 5),
                      np.arange(25, 50, dtype="float32").reshape(5, 5)]
                     )[None]                                # [1, 2, 5, 5]
        w = np.eye(2, dtype="float32").reshape(2, 2, 1, 1)  # identity mix
        off = np.zeros((1, 2 * 1 * 2, 5, 5), "float32")
        off[:, 0:2] = 1.0  # dg block 0: dy=dx=+1
        got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                            paddle.to_tensor(w),
                            deformable_groups=2).numpy()
        ref0 = np.zeros((5, 5), "float32")
        ref0[:4, :4] = x[0, 0, 1:, 1:]
        np.testing.assert_allclose(got[0, 0], ref0, atol=1e-5)
        np.testing.assert_allclose(got[0, 1], x[0, 1], atol=1e-5)


class TestBoxCoder:
    def test_encode_manual(self):
        prior = np.asarray([[0.0, 0.0, 10.0, 10.0]], "float32")
        target = np.asarray([[2.0, 2.0, 8.0, 8.0]], "float32")
        out = box_coder(paddle.to_tensor(prior), [0.1, 0.1, 0.2, 0.2],
                        paddle.to_tensor(target)).numpy()
        # centers: prior (5,5) w=h=10; target (5,5) w=h=6
        ox = (5.0 - 5.0) / 10.0 / 0.1
        ow = np.log(6.0 / 10.0) / 0.2
        np.testing.assert_allclose(out[0, 0], [ox, ox, ow, ow], rtol=1e-5)

    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(0)
        M, N = 4, 6
        xy = rng.rand(M, 2) * 50
        prior = np.concatenate([xy, xy + 1 + rng.rand(M, 2) * 20],
                               axis=1).astype("f4")
        txy = rng.rand(N, 2) * 50
        target = np.concatenate([txy, txy + 1 + rng.rand(N, 2) * 20],
                                axis=1).astype("f4")
        var = [0.1, 0.1, 0.2, 0.2]
        enc = box_coder(paddle.to_tensor(prior), var,
                        paddle.to_tensor(target), code_type="encode")
        dec = box_coder(paddle.to_tensor(prior), var, enc,
                        code_type="decode", axis=0).numpy()
        # decoding the encoding against the same priors recovers targets
        for j in range(M):
            np.testing.assert_allclose(dec[:, j], target, rtol=1e-4,
                                       atol=1e-3)

    def test_unnormalized_boxes(self):
        prior = np.asarray([[0.0, 0.0, 9.0, 9.0]], "float32")  # w=h=10
        target = np.asarray([[0.0, 0.0, 9.0, 9.0]], "float32")
        enc = box_coder(paddle.to_tensor(prior), None,
                        paddle.to_tensor(target),
                        box_normalized=False).numpy()
        np.testing.assert_allclose(enc[0, 0], [0, 0, 0, 0], atol=1e-6)


class TestYoloBox:
    def test_manual_single_cell(self):
        # 1 anchor, 1 class, 1x1 grid: verify the decode formulas
        A, cls, H = 1, 1, 1
        t = np.zeros((1, A * (5 + cls), H, H), "float32")
        t[0, 0] = 0.0   # tx -> sigmoid=0.5 -> cx=(0.5+0)/1
        t[0, 1] = 0.0
        t[0, 2] = 0.0   # tw -> bw = anchor_w / (32*1)
        t[0, 3] = 0.0
        t[0, 4] = 5.0   # high objectness
        t[0, 5] = 0.0   # class logit -> 0.5
        img = np.asarray([[64, 64]], "int32")
        boxes, scores = yolo_box(paddle.to_tensor(t), paddle.to_tensor(img),
                                 anchors=[16, 16], class_num=cls,
                                 downsample_ratio=32)
        b = boxes.numpy()[0, 0]
        cx, bw = 0.5, 16.0 / 32.0
        exp = np.asarray([(cx - bw / 2) * 64, (cx - bw / 2) * 64,
                          (cx + bw / 2) * 64, (cx + bw / 2) * 64])
        np.testing.assert_allclose(b, exp, rtol=1e-5)
        conf = 1.0 / (1.0 + np.exp(-5.0))
        np.testing.assert_allclose(scores.numpy()[0, 0, 0], conf * 0.5,
                                   rtol=1e-5)

    def test_conf_thresh_zeroes(self):
        t = np.zeros((1, 6, 2, 2), "float32")
        t[0, 4] = -10.0  # objectness ~ 0
        img = np.asarray([[32, 32]], "int32")
        boxes, scores = yolo_box(paddle.to_tensor(t), paddle.to_tensor(img),
                                 anchors=[8, 8], class_num=1,
                                 conf_thresh=0.5)
        assert np.all(boxes.numpy() == 0)
        assert np.all(scores.numpy() == 0)

    def test_clip_bbox(self):
        t = np.zeros((1, 6, 1, 1), "float32")
        t[0, 2] = 3.0   # huge width -> clips to image
        t[0, 3] = 3.0
        t[0, 4] = 5.0
        img = np.asarray([[40, 40]], "int32")
        boxes, _ = yolo_box(paddle.to_tensor(t), paddle.to_tensor(img),
                            anchors=[32, 32], class_num=1,
                            downsample_ratio=32, clip_bbox=True)
        b = boxes.numpy()[0, 0]
        assert b[0] >= 0 and b[1] >= 0 and b[2] <= 39 and b[3] <= 39


class TestRoiPool:
    def test_roi_pool_values(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        boxes = np.array([[0.0, 0.0, 3.0, 3.0]], "float32")
        got = roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                       paddle.to_tensor(np.array([1], "int32")),
                       output_size=2).numpy()
        # 2x2 max pooling over the full 4x4 box
        ref = np.array([[[[5.0, 7.0], [13.0, 15.0]]]], "float32")
        np.testing.assert_allclose(got, ref)

    def test_psroi_pool_shape_and_mean(self):
        # C = out_c * ph * pw = 2*2*2 = 8
        x = np.ones((1, 8, 4, 4), "float32")
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], "float32")
        got = psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], "int32")),
                         output_size=2).numpy()
        assert got.shape == (1, 2, 2, 2)
        np.testing.assert_allclose(got, np.ones((1, 2, 2, 2)), rtol=1e-6)

    def test_psroi_pool_channel_major_order(self):
        # channel k filled with value k: out channel c bin (i,j) must read
        # input channel c*ph*pw + i*pw + j (R-FCN channel-major layout)
        C, ph, pw = 8, 2, 2
        x = np.zeros((1, C, 4, 4), "float32")
        for k in range(C):
            x[0, k] = k
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], "float32")
        got = psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], "int32")),
                         output_size=2).numpy()
        ref = np.zeros((1, 2, ph, pw), "float32")
        for c in range(2):
            for i in range(ph):
                for j in range(pw):
                    ref[0, c, i, j] = c * ph * pw + i * pw + j
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_batched_input_supported(self):
        # r3: N>1 via boxes_num now works (was NotImplementedError)
        x = np.ones((2, 8, 4, 4), "float32")
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], "float32")
        out = psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1, 0], "int32")), 2)
        assert tuple(out.shape) == (1, 2, 2, 2)
        out2 = roi_pool(paddle.to_tensor(np.ones((2, 1, 4, 4), "float32")),
                        paddle.to_tensor(boxes),
                        paddle.to_tensor(np.array([1, 0], "int32")), 2)
        assert tuple(out2.shape) == (1, 1, 2, 2)


def test_roi_pool_batched_matches_per_image():
    """N>1 with boxes_num (VERDICT r2 missing #5): batched call ==
    single-image calls concatenated."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import roi_pool
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 16, 16).astype("f4")
    b0 = np.asarray([[0, 0, 7, 7], [4, 4, 12, 12]], "f4")
    b1 = np.asarray([[2, 2, 10, 10]], "f4")
    boxes = np.concatenate([b0, b1])
    out = roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                   np.asarray([2, 1], "i4"), output_size=4)
    ref0 = roi_pool(paddle.to_tensor(x[:1]), paddle.to_tensor(b0),
                    np.asarray([2], "i4"), output_size=4)
    ref1 = roi_pool(paddle.to_tensor(x[1:]), paddle.to_tensor(b1),
                    np.asarray([1], "i4"), output_size=4)
    np.testing.assert_allclose(out.numpy()[:2], ref0.numpy(), rtol=1e-6)
    np.testing.assert_allclose(out.numpy()[2:], ref1.numpy(), rtol=1e-6)


def test_psroi_pool_batched_matches_per_image():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import psroi_pool
    rng = np.random.RandomState(1)
    x = rng.rand(2, 2 * 3 * 3, 12, 12).astype("f4")
    b0 = np.asarray([[0, 0, 6, 6]], "f4")
    b1 = np.asarray([[3, 3, 11, 11], [1, 1, 8, 8]], "f4")
    boxes = np.concatenate([b0, b1])
    out = psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                     np.asarray([1, 2], "i4"), output_size=3)
    ref0 = psroi_pool(paddle.to_tensor(x[:1]), paddle.to_tensor(b0),
                      np.asarray([1], "i4"), output_size=3)
    ref1 = psroi_pool(paddle.to_tensor(x[1:]), paddle.to_tensor(b1),
                      np.asarray([2], "i4"), output_size=3)
    np.testing.assert_allclose(out.numpy()[:1], ref0.numpy(), rtol=1e-6)
    np.testing.assert_allclose(out.numpy()[1:], ref1.numpy(), rtol=1e-6)


def test_round4_detection_ops():
    """prior_box / distribute_fpn_proposals / matrix_nms /
    generate_proposals / RoI layer wrappers (reference:
    paddle.vision.ops detection family)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision import ops as V

    feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), "f4"))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), "f4"))
    boxes, var = V.prior_box(feat, img, min_sizes=[16], max_sizes=[32],
                             aspect_ratios=[2.0], flip=True, clip=True)
    assert tuple(boxes.shape) == (4, 4, 4, 4)
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 1).all()
    # anchor centers follow the offset*step grid
    np.testing.assert_allclose((b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2,
                               (0.5 * 16) / 64, atol=1e-6)

    rois = np.asarray([[0, 0, 10, 10], [0, 0, 100, 100],
                       [0, 0, 500, 500]], "f4")
    multi, restore = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224)
    assert sum(m.shape[0] for m in multi) == 3
    assert sorted(restore.numpy().ravel().tolist()) == [0, 1, 2]

    bb = np.asarray([[[0, 0, 10, 10], [0, 0, 10, 10],
                      [20, 20, 30, 30]]], "f4")
    ss = np.zeros((1, 2, 3), "f4")
    ss[0, 1] = [0.9, 0.8, 0.7]
    out, nums = V.matrix_nms(paddle.to_tensor(bb), paddle.to_tensor(ss),
                             score_threshold=0.1, post_threshold=0.2,
                             nms_top_k=10, keep_top_k=5)
    o = out.numpy()
    assert o.shape[1] == 6 and int(nums.numpy()[0]) >= 2
    # identical twin decays: its soft score drops below the leader's
    assert o[0, 1] >= o[1, 1]

    H = W = 4
    A = 3
    sc = np.random.RandomState(0).rand(1, A, H, W).astype("f4")
    bd = np.zeros((1, 4 * A, H, W), "f4")
    anchors = np.random.RandomState(1).rand(H, W, A, 4).astype("f4") * 32
    anchors[..., 2:] += anchors[..., :2] + 8
    var = np.full((H, W, A, 4), 0.1, "f4")
    rois2, rs, rn = V.generate_proposals(
        paddle.to_tensor(sc), paddle.to_tensor(bd),
        paddle.to_tensor(np.asarray([[64, 64]], "f4")),
        paddle.to_tensor(anchors), paddle.to_tensor(var),
        pre_nms_top_n=20, post_nms_top_n=5, return_rois_num=True)
    assert rois2.shape[0] <= 5 and int(rn.numpy()[0]) == rois2.shape[0]
    # zero deltas -> proposals are clipped anchors: inside the image
    r = rois2.numpy()
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 64).all()

    x = paddle.to_tensor(np.random.rand(1, 4, 8, 8).astype("f4"))
    box1 = paddle.to_tensor(np.asarray([[0, 0, 7, 7]], "f4"))
    bn = paddle.to_tensor(np.asarray([1], "i4"))
    assert tuple(V.RoIAlign(2)(x, box1, bn).shape) == (1, 4, 2, 2)
    assert tuple(V.RoIPool(2)(x, box1, bn).shape) == (1, 4, 2, 2)
    assert tuple(V.PSRoIPool(2, 1.0)(x, box1, bn).shape) == (1, 1, 2, 2)


def test_yolo_loss_basics():
    """yolo_loss (reference: paddle.vision.ops.yolo_loss) — finite
    per-image losses, gradient flow, responsiveness to gt presence,
    and jit-compatibility (traced scatter assignment)."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.vision import ops as V

    rng = np.random.RandomState(0)
    N, A, C, H, W = 2, 3, 4, 8, 8
    anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
               116, 90, 156, 198, 373, 326]
    mask = [0, 1, 2]
    xv = rng.randn(N, A * (5 + C), H, W).astype("f4") * 0.1
    gt = np.zeros((N, 5, 4), "f4")
    gt[0, 0] = [0.5, 0.5, 0.1, 0.15]
    gt[1, 0] = [0.6, 0.3, 0.12, 0.1]
    gl = np.zeros((N, 5), "i4")
    gl[0, 0] = 2

    x = paddle.to_tensor(xv, stop_gradient=False)
    loss = V.yolo_loss(x, paddle.to_tensor(gt), paddle.to_tensor(gl),
                       anchors, mask, C, ignore_thresh=0.7,
                       downsample_ratio=32)
    assert loss.shape == [N] and np.isfinite(loss.numpy()).all()
    loss.sum().backward()
    assert np.abs(x.grad.numpy()).sum() > 0

    # objectness target responds to gt: loss differs from the empty case
    empty = V.yolo_loss(paddle.to_tensor(xv),
                        paddle.to_tensor(np.zeros((N, 5, 4), "f4")),
                        paddle.to_tensor(np.zeros((N, 5), "i4")),
                        anchors, mask, C, 0.7, 32)
    assert not np.allclose(loss.numpy(), empty.numpy())

    # jits (traced gt): same numbers as eager
    import jax.numpy as jnp
    jl = jax.jit(lambda xv_, gb, lb: V.yolo_loss(
        paddle.Tensor(xv_), paddle.Tensor(gb), paddle.Tensor(lb),
        anchors, mask, C, 0.7, 32)._value)(
        jnp.asarray(xv), jnp.asarray(gt), jnp.asarray(gl))
    np.testing.assert_allclose(np.asarray(jl), loss.numpy(), rtol=1e-4)
