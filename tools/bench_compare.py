#!/usr/bin/env python
"""Bench trajectory regression gate: diff the newest two BENCH_r*.json
(tokens/sec, MFU, serving useful-tok/s, validity flags) and exit
non-zero when a tracked metric drops past the threshold or a config's
validity regresses.

Thin wrapper over ``paddle_tpu.analysis.bench_gate`` (the same logic
runs as the opt-in ``bench`` lint pass: ``python tools/lint.py
--passes bench``).  Threshold: ``--threshold 0.05`` (relative drop) or
the ``PADDLE_BENCH_THRESHOLD`` env; see docs/observability.md.

Usage:
    python tools/bench_compare.py                 # newest two in repo
    python tools/bench_compare.py OLD.json NEW.json
    python tools/bench_compare.py --threshold 0.10 --json

Exit codes: 0 no regression, 1 regression(s), 2 usage/unreadable.
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.analysis import bench_gate  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Diff two bench artifacts; exit 1 on regression.")
    ap.add_argument("files", nargs="*",
                    help="OLD.json NEW.json (default: the newest two "
                         "BENCH_r*.json in the repo root)")
    ap.add_argument("--threshold", type=float, default=None,
                    help="relative drop that fails the gate (default "
                         f"{bench_gate.DEFAULT_THRESHOLD}, or "
                         f"${bench_gate.THRESHOLD_ENV})")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.files and len(args.files) != 2:
        print("error: pass exactly two files (or none)", file=sys.stderr)
        return 2
    if args.files:
        old_p, new_p = args.files
    else:
        files = bench_gate.bench_files(REPO)
        if len(files) < 2:
            print(f"nothing to diff: {len(files)} BENCH_r*.json under "
                  f"{REPO} (need 2)")
            return 0
        old_p, new_p = files[-2], files[-1]
    try:
        rows = bench_gate.compare(bench_gate.load_bench(old_p),
                                  bench_gate.load_bench(new_p),
                                  threshold=args.threshold)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    regressions = [r for r in rows if r["regressed"]]
    if args.as_json:
        print(json.dumps({"old": old_p, "new": new_p, "rows": rows,
                          "regressions": len(regressions)},
                         indent=1, sort_keys=True))
        return 1 if regressions else 0
    print(f"bench diff: {os.path.basename(old_p)} -> "
          f"{os.path.basename(new_p)}")
    for r in rows:
        mark = "REGRESSED" if r["regressed"] else "ok"
        delta = f" ({r['delta']:+.1%})" if r["delta"] is not None else ""
        why = f" — {r['why']}" if r["why"] else ""
        print(f"  [{mark:>9}] {r['key']}: {r['old']} -> "
              f"{r['new']}{delta}{why}")
    if regressions:
        print(f"FAIL: {len(regressions)} regression(s)")
        return 1
    print("OK: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
