#!/usr/bin/env python
"""Lint: every failpoint name referenced by tests/docs must exist in the
registry (paddle_tpu/framework/failpoints.py sites register at import).

A renamed or deleted hook site would otherwise leave chaos tests arming a
failpoint that can never fire — the test silently stops testing anything.

Thin wrapper over the unified static-analysis runner (the pass itself
lives in paddle_tpu/analysis/registry_lints.py; ``python tools/lint.py``
runs it together with the other passes).

Usage: python tools/check_failpoints.py   (exit 0 clean, 1 on orphans)
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--passes", "failpoint-refs", "--no-baseline"]))
