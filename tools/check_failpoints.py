#!/usr/bin/env python
"""Lint: every failpoint name referenced by tests/docs must exist in the
registry (paddle_tpu/framework/failpoints.py sites register at import).

A renamed or deleted hook site would otherwise leave chaos tests arming a
failpoint that can never fire — the test silently stops testing anything.

Usage: python tools/check_failpoints.py   (exit 0 clean, 1 on orphans)
"""
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# importing the hooked modules populates the registry
from paddle_tpu.framework import failpoints  # noqa: E402
import paddle_tpu.framework.guardian  # noqa: F401,E402
import paddle_tpu.distributed.store  # noqa: F401,E402
import paddle_tpu.distributed.checkpoint  # noqa: F401,E402
import paddle_tpu.distributed.collective  # noqa: F401,E402
import paddle_tpu.distributed.fleet.elastic  # noqa: F401,E402
import paddle_tpu.io.worker  # noqa: F401,E402

# name references: set_failpoint("<name>", ...) and spec strings of the
# PADDLE_FAILPOINTS form "<name>=<action>[;...]"
_SET_RE = re.compile(r"set_failpoint\(\s*[\"']([^\"']+)[\"']")
_SPEC_RE = re.compile(
    r"[\"']([a-z0-9_]+(?:\.[a-z0-9_]+)+=[^\"']+)[\"']")


def references(text, known_prefixes):
    """set_failpoint("...") names are always checked; spec-shaped
    strings count only when their name carries a registered subsystem
    prefix (store./ckpt./...) — an unrelated "retry.mode=skip" literal
    elsewhere in a test must not fail this lint."""
    names = set(_SET_RE.findall(text))
    for spec in _SPEC_RE.findall(text):
        try:
            parsed = failpoints.parse_spec(spec)
        except ValueError:
            continue    # string merely looks spec-shaped; not a spec
        names.update(n for n in parsed
                     if n.split(".", 1)[0] in known_prefixes)
    return names


def main():
    roots = [os.path.join(REPO, "tests"), os.path.join(REPO, "docs")]
    known = failpoints.registered()
    known_prefixes = {n.split(".", 1)[0] for n in known}
    bad = []
    for root in roots:
        for dirpath, _, files in os.walk(root):
            for fn in files:
                if not fn.endswith((".py", ".md")):
                    continue
                path = os.path.join(dirpath, fn)
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                for name in sorted(references(text, known_prefixes)
                                   - known):
                    bad.append((os.path.relpath(path, REPO), name))
    if bad:
        print("unknown failpoint name(s) referenced:")
        for path, name in bad:
            print(f"  {path}: {name!r}")
        print(f"registered sites: {', '.join(sorted(known))}")
        return 1
    print(f"OK: all failpoint references resolve "
          f"({len(known)} registered sites)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
