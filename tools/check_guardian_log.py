#!/usr/bin/env python
"""Lint: guardian-log events referenced by tests/docs must match the
emitter's schema (paddle_tpu/framework/guardian.py EVENT_SCHEMA).

Two contracts, both directions:

1. Every event name a test or doc references — ``emit("name", ...)``,
   ``events("name")``, or a ``| `name` | ... |`` row of the schema table
   in docs/training_guardian.md — must exist in EVENT_SCHEMA (a renamed
   event must not leave tests silently asserting on an empty filter).
2. The docs schema table must list every EVENT_SCHEMA event with
   exactly the emitter's field set — dashboards are built from the doc,
   so a drifted table is a lying contract.

Usage: python tools/check_guardian_log.py   (exit 0 clean, 1 on drift)
"""
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.framework.guardian import EVENT_SCHEMA  # noqa: E402

DOC = os.path.join(REPO, "docs", "training_guardian.md")

# emit("name", ...) / events("name") / events(event="name")
_CALL_RE = re.compile(
    r"\b(?:emit|events)\(\s*(?:event\s*=\s*)?[\"']([a-z_]+)[\"']")
# docs schema table row: | `event_name` | `field, field, ...` |
_ROW_RE = re.compile(r"^\|\s*`([a-z_]+)`\s*\|\s*`([^`]*)`", re.M)


def code_references():
    refs = []    # (relpath, name)
    for root in (os.path.join(REPO, "tests"), os.path.join(REPO, "docs")):
        for dirpath, _, files in os.walk(root):
            for fn in files:
                if not fn.endswith((".py", ".md")):
                    continue
                path = os.path.join(dirpath, fn)
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                for name in _CALL_RE.findall(text):
                    refs.append((os.path.relpath(path, REPO), name))
    return refs


def doc_table():
    """{event: {fields}} parsed from the docs schema table."""
    if not os.path.exists(DOC):
        return None
    with open(DOC, encoding="utf-8") as f:
        text = f.read()
    out = {}
    for name, fields in _ROW_RE.findall(text):
        out[name] = {f.strip() for f in fields.split(",") if f.strip()}
    return out


def main():
    problems = []
    for path, name in code_references():
        if name not in EVENT_SCHEMA:
            problems.append(f"{path}: unknown guardian event {name!r}")
    table = doc_table()
    if table is None:
        problems.append(f"{os.path.relpath(DOC, REPO)}: missing (the "
                        "guardian log schema must be documented)")
    else:
        for name, fields in table.items():
            if name not in EVENT_SCHEMA:
                problems.append(
                    f"docs/training_guardian.md: documents unknown event "
                    f"{name!r}")
            elif fields != EVENT_SCHEMA[name]:
                problems.append(
                    f"docs/training_guardian.md: event {name!r} fields "
                    f"{sorted(fields)} drifted from emitter schema "
                    f"{sorted(EVENT_SCHEMA[name])}")
        for name in EVENT_SCHEMA:
            if name not in table:
                problems.append(
                    f"docs/training_guardian.md: event {name!r} is "
                    "emitted but undocumented")
    if problems:
        print("guardian log schema drift:")
        for p in problems:
            print(f"  {p}")
        print(f"emitter schema: {', '.join(sorted(EVENT_SCHEMA))}")
        return 1
    print(f"OK: guardian log references and docs match the emitter "
          f"schema ({len(EVENT_SCHEMA)} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
