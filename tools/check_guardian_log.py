#!/usr/bin/env python
"""Lint: guardian-log events referenced by tests/docs must match the
emitter's schema (paddle_tpu/framework/guardian.py EVENT_SCHEMA), and
the docs schema table must mirror it field-for-field — dashboards are
built from the doc, so a drifted table is a lying contract.

Thin wrapper over the unified static-analysis runner (the pass itself
lives in paddle_tpu/analysis/registry_lints.py; ``python tools/lint.py``
runs it together with the other passes).

Usage: python tools/check_guardian_log.py   (exit 0 clean, 1 on drift)
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--passes", "guardian-log", "--no-baseline"]))
