#!/usr/bin/env python
"""Single CI entry point: lint sweep -> tier-1 tests -> opt-in bench
gate, in that order, stopping at the first failing stage.

The three gates existed separately (`tools/lint.py`, the tier-1 pytest
invocation from ROADMAP.md, `tools/bench_compare.py`); nothing ran them
as one pipeline, so "is this tree green" was three commands and a
README lookup.  This wires them into one:

    python tools/ci_check.py                  # lint + tests
    python tools/ci_check.py --changed-only   # git-diff-scoped lint,
                                              # then tests
    python tools/ci_check.py --bench-gate     # + BENCH_r* trajectory
                                              # diff (opt-in: bench
                                              # numbers move with
                                              # machine load)
    python tools/ci_check.py --doctor         # + doctor smoke over the
                                              # committed telemetry/
                                              # snapshots (healthy ->
                                              # 'no alerts', exit 0)
    python tools/ci_check.py --chaos          # + the chaos-marked
                                              # elastic-resume + PD-
                                              # handoff suites (opt-in:
                                              # kill/resume e2e is
                                              # slower than tier-1
                                              # unit tests)
    python tools/ci_check.py --kernels        # + the Pallas kernel /
                                              # registry suites with
                                              # interpret mode forced
                                              # (selected TPU kernels
                                              # run on the CPU backend)
    python tools/ci_check.py --obs            # + the observability
                                              # suites (HBM memory
                                              # ledger, tracing, flight
                                              # recorder / watchdog)
    python tools/ci_check.py --skip-tests     # lint (+gate) only
    python tools/ci_check.py --lint-only      # lint sweep alone: the
                                              # pre-commit fast path
                                              # (<10s, no pytest, no
                                              # opt-in gates)

Stages:

1. **lint** — the full static-analysis suite (`python -m
   paddle_tpu.analysis`, baseline-suppressed).  `--changed-only`
   passes through to the runner's git-diff scoping.
2. **tests** — tier-1: ``pytest tests/ -m 'not slow'`` on the CPU
   backend (the ROADMAP.md verify command without the log plumbing).
   ``--pytest-args "..."`` appends extra flags (e.g. ``-x -k serving``).
3. **bench gate** (``--bench-gate``) — diff the newest two committed
   ``BENCH_r*.json`` via the `bench` pass (threshold:
   ``PADDLE_BENCH_THRESHOLD``, default 5%).

Exit code: the first failing stage's (lint/bench: 1; tests: pytest's).
"""
import argparse
import os
import shlex
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _stage(name):
    print(f"\n=== ci_check: {name} ===", flush=True)
    return time.perf_counter()


def run_lint(changed_only):
    from paddle_tpu.analysis import main as lint_main
    t0 = _stage("lint sweep" + (" (--changed-only)" if changed_only
                                else ""))
    argv = ["--changed-only"] if changed_only else []
    rc = lint_main(argv)
    print(f"lint: {'OK' if rc == 0 else 'FAIL'} "
          f"({time.perf_counter() - t0:.1f}s)")
    return rc


def run_tests(extra):
    t0 = _stage("tier-1 tests (pytest -m 'not slow')")
    cmd = [sys.executable, "-m", "pytest", "tests/", "-q",
           "-m", "not slow", "--continue-on-collection-errors",
           "-p", "no:cacheprovider"] + extra
    print("$", " ".join(shlex.quote(c) for c in cmd), flush=True)
    rc = subprocess.call(cmd, cwd=REPO)
    print(f"tests: {'OK' if rc == 0 else f'FAIL (rc={rc})'} "
          f"({time.perf_counter() - t0:.1f}s)")
    return rc


def run_doctor():
    """Doctor smoke over the committed telemetry/ snapshots: every
    artifact must parse clean and yield the 'no alerts' verdict, so
    the committed files and the doctor/report parsers can never drift
    apart (the ISSUE 13 CI satellite; opt-in like the bench gate)."""
    import glob
    from paddle_tpu.observability import doctor
    t0 = _stage("doctor smoke over committed telemetry/ (opt-in)")
    tdir = os.path.join(REPO, "telemetry")
    proms = sorted(glob.glob(os.path.join(tdir, "*.prom")))
    if not proms:
        print("doctor: no committed telemetry snapshots found")
        return 1
    rc = 0
    for prom in proms:
        tag = os.path.splitext(os.path.basename(prom))[0]
        jsonl = os.path.join(tdir, tag + ".jsonl")
        trace = os.path.join(tdir, tag + "_requests.trace.json")
        ev = doctor.evidence_from_sinks(
            prom=prom,
            jsonl=jsonl if os.path.exists(jsonl) else None,
            trace=trace if os.path.exists(trace) else None)
        result = doctor.diagnose(ev)
        healthy = result["verdict"] == "no alerts"
        print(f"  {tag}: verdict={result['verdict']!r} "
              f"({len(result['sources'])} sink(s), "
              f"{len(result['diagnoses'])} signal(s))")
        for note in result["notes"]:
            print(f"    note: {note}")
        if not healthy:
            for d in result["diagnoses"][:3]:
                for e in d["evidence"]:
                    print(f"    [{d['cause']}] {e}")
            rc = 1
    print(f"doctor: {'OK' if rc == 0 else 'FAIL'} "
          f"({time.perf_counter() - t0:.1f}s)")
    return rc


def run_chaos():
    """Chaos stage (the ISSUE 14 CI satellite, opt-in): run the
    `chaos`-marked suites — elastic-resume (manifest save/restore
    across topology changes, the np=8 → np=4 kill/resume e2e,
    retention/read races) on the 8-virtual-device CPU-proxy mesh the
    tests/conftest forces, plus the prefill/decode handoff chaos suite
    (dropped/corrupt bundles, reservation expiry, mid-transfer prefill
    death — bitwise fallback, zero leaked pages)."""
    t0 = _stage("chaos suites (opt-in: elastic resume + handoff)")
    cmd = [sys.executable, "-m", "pytest",
           "tests/test_elastic_resume.py", "tests/test_fault_tolerance.py",
           "tests/test_handoff.py",
           "-q", "-m", "chaos", "--continue-on-collection-errors",
           "-p", "no:cacheprovider"]
    print("$", " ".join(shlex.quote(c) for c in cmd), flush=True)
    rc = subprocess.call(cmd, cwd=REPO)
    print(f"chaos: {'OK' if rc == 0 else f'FAIL (rc={rc})'} "
          f"({time.perf_counter() - t0:.1f}s)")
    return rc


def run_kernels():
    """Kernel stage (the ISSUE 15 CI satellite, opt-in): run the Pallas
    kernel + registry suites with interpret mode forced, so the
    *selected* TPU kernels — dispatch, padding, masks, custom VJPs —
    execute end to end on the CPU backend (the same parity contract
    the train-step tests machine-check)."""
    t0 = _stage("interpret-mode kernel suite (opt-in)")
    cmd = [sys.executable, "-m", "pytest",
           "tests/test_flash_attention.py", "tests/test_fused_xent.py",
           "tests/test_pallas_fused.py", "tests/test_quant_matmul.py",
           "tests/test_varlen_attention.py",
           "tests/test_kernel_registry.py", "tests/test_quant_paths.py",
           "-q", "--continue-on-collection-errors",
           "-p", "no:cacheprovider"]
    env = {**os.environ, "PADDLE_TPU_KERNEL_INTERPRET": "1"}
    print("$ PADDLE_TPU_KERNEL_INTERPRET=1",
          " ".join(shlex.quote(c) for c in cmd), flush=True)
    rc = subprocess.call(cmd, cwd=REPO, env=env)
    print(f"kernels: {'OK' if rc == 0 else f'FAIL (rc={rc})'} "
          f"({time.perf_counter() - t0:.1f}s)")
    return rc


def run_obs():
    """Observability stage (the ISSUE 20 CI satellite, opt-in): run
    the memory-ledger + tracing/compile-telemetry + flight/watchdog
    suites — the HBM ledger, the hbm_pressure watchdog path, the
    dropped-spans accounting and the bundle retention discipline."""
    t0 = _stage("observability suites (opt-in: memory + tracing)")
    cmd = [sys.executable, "-m", "pytest",
           "tests/test_memory_ledger.py", "tests/test_compile_tracing.py",
           "tests/test_flight_watchdog.py", "tests/test_observability.py",
           "-q", "--continue-on-collection-errors",
           "-p", "no:cacheprovider"]
    print("$", " ".join(shlex.quote(c) for c in cmd), flush=True)
    rc = subprocess.call(cmd, cwd=REPO)
    print(f"obs: {'OK' if rc == 0 else f'FAIL (rc={rc})'} "
          f"({time.perf_counter() - t0:.1f}s)")
    return rc


def run_bench_gate():
    from paddle_tpu.analysis import runner
    t0 = _stage("bench trajectory gate (opt-in)")
    findings = runner.run_passes(passes=["bench"])
    for f in findings:
        print(f"  [{f.code}] {f.message}")
    rc = 1 if any(f.code in ("bench-regression", "bench-coverage")
                  for f in findings) else 0
    print(f"bench gate: {'OK' if rc == 0 else 'FAIL'} "
          f"({time.perf_counter() - t0:.1f}s)")
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="lint sweep -> tier-1 pytest -> opt-in bench gate")
    ap.add_argument("--changed-only", action="store_true",
                    help="scope the lint sweep to the git diff "
                         "(tests still run in full)")
    ap.add_argument("--bench-gate", action="store_true",
                    help="also diff the newest two BENCH_r*.json")
    ap.add_argument("--doctor", action="store_true",
                    help="also run the doctor smoke over the committed "
                         "telemetry/ snapshots (healthy artifacts must "
                         "parse clean with a 'no alerts' verdict)")
    ap.add_argument("--chaos", action="store_true",
                    help="also run the chaos-marked elastic-resume "
                         "tests (8-device CPU-proxy mesh) and the "
                         "prefill/decode handoff chaos suite")
    ap.add_argument("--kernels", action="store_true",
                    help="also run the Pallas kernel + registry suites "
                         "with interpret mode forced (the selected TPU "
                         "kernels execute on the CPU backend)")
    ap.add_argument("--obs", action="store_true",
                    help="also run the observability suites (HBM "
                         "memory ledger, tracing, flight recorder / "
                         "watchdog)")
    ap.add_argument("--skip-tests", action="store_true",
                    help="lint (and gate) only")
    ap.add_argument("--lint-only", action="store_true",
                    help="run the lint sweep alone and stop — the "
                         "pre-commit fast path (no pytest, no opt-in "
                         "gates; combine with --changed-only for the "
                         "inner loop)")
    ap.add_argument("--pytest-args", default="",
                    help="extra pytest flags, quoted (e.g. '-x -k "
                         "serving')")
    args = ap.parse_args(argv)

    rc = run_lint(args.changed_only)
    if rc != 0:
        return rc
    if args.lint_only:
        print("\nci_check: LINT GREEN (--lint-only: tests and gates "
              "skipped)")
        return 0
    if args.doctor:
        rc = run_doctor()
        if rc != 0:
            return rc
    if args.bench_gate:
        rc = run_bench_gate()
        if rc != 0:
            return rc
    if args.obs:
        rc = run_obs()
        if rc != 0:
            return rc
    if args.chaos:
        rc = run_chaos()
        if rc != 0:
            return rc
    if args.kernels:
        rc = run_kernels()
        if rc != 0:
            return rc
    if not args.skip_tests:
        rc = run_tests(shlex.split(args.pytest_args))
        if rc != 0:
            return rc
    print("\nci_check: ALL GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
