"""Round-5 fp8 kernel experiment log + re-runnable probe.

Question (VERDICT r4 #1): can a Pallas kernel make the M=32 serving
fp8 linear weight-bandwidth-bound (r4 artifact said 85 GB/s, 0.72x
vs bf16)?

Answer (measured on v5e, scan-chained reps so the ~95 ms tunnel
dispatch latency is amortized/subtracted — the r4 numbers in BOTH
directions were latency noise):

  bf16 XLA dot chain     : 1.46 ms/pass  733 GB/s weight stream
  fp8 XLA weight-only    : 0.88 ms/pass  609 GB/s (of half-size
                           weights) = **1.66x**  <- shipped path
  int8 Pallas (MXU-native): 1.11 ms/pass = 1.32x (shipped as the
                           int8_matmul small-M config)
  fp8 Pallas attempts    : all LOSE to the XLA path —
    native `.astype(bf16)` of an fp8 ref   ~10 ms/pass (scalar-slow)
    bit-twiddle int32 upconvert            ~3.9 ms  (VPU-bound)
    scale-folded twiddle ((u&0x7F)<<4,
      x2^120 folded into channel scale)    ~3.6 ms
    packed-int32 + channel-shuffled bytes  ~3.4 ms
  (those Pallas numbers carry ~1.9 ms latency share at reps=50;
  even latency-corrected they sit ~1.5-2 ms, above XLA's 0.88.)

Conclusion: XLA already streams fp8 weights near the HBM roofline and
fuses the upconvert into the matmul's weight loop; a Pallas upconvert
kernel only adds VPU work in front of the MXU.  fp8_matmul therefore
deliberately has NO Pallas path (see its docstring), and the win
shipped as the weight-only default + the scan-chained bench.

Usage: python tools/fp8_tune.py bk bn [twiddle|mul|mul_unroll]
re-runs the historical Pallas probe at one block config.
"""
import sys, time, functools, numpy as np, jax, jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.compat import TPUCompilerParams

bk, bn = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (4096, 1024)
mode = sys.argv[3] if len(sys.argv) > 3 else "mul"
M,K,N,L,R = 32,4096,4096,32,500

rng = np.random.RandomState(0)
Wf = rng.randn(L,K,N).astype('f4')*0.02
sc = np.maximum(np.abs(Wf).max(axis=1)/448.0, 1e-12)
q = jnp.asarray(Wf/sc[:,None,:], jnp.float8_e4m3fn)
u = np.asarray(lax.bitcast_convert_type(q, jnp.uint8))
u = np.where((u & 0x78) == 0, u & 0x80, u)                     # FTZ
W8 = jnp.asarray(u)
S = jnp.asarray(sc * (2.0**120 if mode.startswith("mul") else 1.0), jnp.float32)
x = jnp.asarray(rng.randn(M,K).astype('f4'), dtype=jnp.bfloat16)
def sync(v): return float(np.asarray(jax.device_get(v)))

def kern(x_ref, w_ref, ws_ref, o_ref, acc_ref, *, n_k):
    k = pl.program_id(1)
    @pl.when(k == 0)
    def _z(): acc_ref[:] = jnp.zeros_like(acc_ref)
    uu = w_ref[:].astype(jnp.int32)
    if mode == "twiddle":
        bits = (((uu & 0x7F) << 4) + 0x3C00) | ((uu >> 7) << 15)
        bits = jnp.where((uu & 0x78) == 0, (uu >> 7) << 15, bits)
    else:  # mul: value = bitcast((u&0x7F)<<4 | sign<<8) * 2^120 (folded into scale)
        bits = ((uu & 0x7F) << 4) | ((uu & 0x80) << 8)
    w = lax.bitcast_convert_type(bits.astype(jnp.uint16), jnp.bfloat16)
    acc_ref[:] += jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)
    @pl.when(k == n_k - 1)
    def _e(): o_ref[:] = (acc_ref[:] * ws_ref[0, :].astype(jnp.float32)[None, :]).astype(o_ref.dtype)

def mm(x, w8, s):
    n_k = K // bk
    return pl.pallas_call(
        functools.partial(kern, n_k=n_k),
        grid=(N // bn, n_k),
        in_specs=[pl.BlockSpec((M, bk), lambda n, k: (0, k)),
                  pl.BlockSpec((bk, bn), lambda n, k: (k, n)),
                  pl.BlockSpec((1, bn), lambda n, k: (0, n))],
        out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((M, bn), jnp.float32)],
        compiler_params=TPUCompilerParams(dimension_semantics=("parallel", "arbitrary")),
    )(x, w8, s.reshape(1, -1))

@jax.jit
def run(x, W8, S):
    def rep(o, _):
        def layer(o, ws):
            w8, s = ws
            return mm(o, w8, s) * 0.01, None
        o, _ = lax.scan(layer, o, (W8, S))
        return o, None
    o, _ = lax.scan(rep, x, None, length=R)
    return jnp.sum(o.astype(jnp.float32))

if __name__ == "__main__":
    t0 = time.perf_counter(); sync(run(x, W8, S)); print(f"compile+first: {time.perf_counter()-t0:.1f}s")
    ts=[]
    for _ in range(3):
        t0=time.perf_counter(); sync(run(x, W8, S)); ts.append((time.perf_counter()-t0)/R)
    t=sorted(ts)[1]
    print(f"{mode} bk={bk} bn={bn}: {t*1e3:.3f} ms/pass, {L*K*N/t/1e9:.0f} GB/s fp8-weight")
