#!/usr/bin/env python
"""Regenerate docs/API_REFERENCE.md — the public-symbol inventory.

Usage:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
            python tools/gen_api_reference.py
"""
import os
import sys
import types
import warnings

warnings.filterwarnings("ignore")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import paddle_tpu as paddle  # noqa: E402


def _mod(name):
    return __import__("paddle_tpu." + name, fromlist=["x"])


SECTIONS = [
    ("paddle", paddle),
    ("paddle.nn", paddle.nn),
    ("paddle.nn.functional", paddle.nn.functional),
    ("paddle.nn.initializer", paddle.nn.initializer),
    ("paddle.nn.utils", paddle.nn.utils),
    ("paddle.nn.quant", paddle.nn.quant),
    ("paddle.tensor (method surface)", None),
    ("paddle.linalg", paddle.linalg),
    ("paddle.fft", paddle.fft),
    ("paddle.signal", paddle.signal),
    ("paddle.optimizer", paddle.optimizer),
    ("paddle.optimizer.lr", paddle.optimizer.lr),
    ("paddle.autograd", paddle.autograd),
    ("paddle.amp", paddle.amp),
    ("paddle.io", paddle.io),
    ("paddle.static", _mod("static")),
    ("paddle.static.nn", _mod("static.nn")),
    ("paddle.static.amp", _mod("static.amp")),
    ("paddle.jit", paddle.jit),
    ("paddle.distributed", paddle.distributed),
    ("paddle.distributed.fleet", paddle.distributed.fleet),
    ("paddle.distributed.fleet.meta_parallel",
     paddle.distributed.fleet.meta_parallel),
    ("paddle.distributed.fleet.utils", paddle.distributed.fleet.utils),
    ("paddle.distributed.sharding", paddle.distributed.sharding),
    ("paddle.distributed.checkpoint", paddle.distributed.checkpoint),
    ("paddle.distributed.rpc", paddle.distributed.rpc),
    ("paddle.distributed.communication",
     paddle.distributed.communication),
    ("paddle.distributed.passes", paddle.distributed.passes),
    ("paddle.vision.models", paddle.vision.models),
    ("paddle.vision.datasets", paddle.vision.datasets),
    ("paddle.vision.transforms", paddle.vision.transforms),
    ("paddle.vision.ops", paddle.vision.ops),
    ("paddle.text", paddle.text),
    ("paddle.audio", paddle.audio),
    ("paddle.metric", paddle.metric),
    ("paddle.hapi (paddle.Model)", _mod("hapi")),
    ("paddle.callbacks", paddle.callbacks),
    ("paddle.distribution", paddle.distribution),
    ("paddle.sparse", paddle.sparse),
    ("paddle.quantization", paddle.quantization),
    ("paddle.incubate", paddle.incubate),
    ("paddle.incubate.nn", paddle.incubate.nn),
    ("paddle.incubate.nn.functional", paddle.incubate.nn.functional),
    ("paddle.geometric", paddle.geometric),
    ("paddle.profiler", paddle.profiler),
    ("paddle.device", paddle.device),
    ("paddle.inference", paddle.inference),
    ("paddle.onnx", paddle.onnx),
    ("paddle.hub", paddle.hub),
    ("paddle.utils", paddle.utils),
]


def public(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in dir(mod) if not n.startswith("_")]
    return [n for n in sorted(set(names))
            if not isinstance(getattr(mod, n, None), types.ModuleType)]


def main():
    lines = ["# paddle_tpu API reference (generated)",
             "",
             "Auto-generated public-symbol inventory, one section per",
             "namespace (regenerate: `python tools/gen_api_reference.py`).",
             "The upstream surface this mirrors is PaddlePaddle 2.5/2.6.",
             ""]
    total = 0
    body = []
    for title, mod in SECTIONS:
        if mod is None:
            from paddle_tpu.framework.core import Tensor
            syms = sorted(n for n in dir(Tensor) if not n.startswith("_"))
        else:
            syms = public(mod)
        total += len(syms)
        unit = "methods" if mod is None else "symbols"
        body.append(f"## {title} — {len(syms)} {unit}\n")
        body.append(", ".join(f"`{s}`" for s in syms) + "\n")
    lines.append(f"**Total: {total} public symbols.**")
    lines.append("")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "API_REFERENCE.md")
    with open(out, "w") as f:
        f.write("\n".join(lines + body))
    print(f"wrote {out}: {total} symbols across {len(SECTIONS)} namespaces")


if __name__ == "__main__":
    main()
