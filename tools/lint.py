#!/usr/bin/env python
"""Unified static-analysis entry point (thin wrapper over
``python -m paddle_tpu.analysis``).

Runs all passes — tracer-safety, host-sync budget, collective-order,
donation, retrace-hazard, concurrency, mesh-axes, dtype-flow,
spec-drift, failpoint-refs, guardian-log, metrics-registry — over the
repo, suppressing findings recorded in
``tools/lint_baseline.json``.  Exit 0 when no NEW findings, 1
otherwise.

Usage:
    python tools/lint.py                 # human output vs baseline
    python tools/lint.py --json          # machine-readable findings
    python tools/lint.py --no-baseline   # everything, no suppression
    python tools/lint.py --update-baseline
    python tools/lint.py --passes tracer-safety,host-sync
    python tools/lint.py --changed-only  # git-diff-scoped inner loop
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
