"""S=4096 MFU ceiling analysis (VERDICT r4 #3) — run on TPU.

Timing rule learned the hard way (see git history of this file): chains
must feed each iteration's OUTPUT tensor back into the next iteration's
INPUT. A scalar carry multiplied onto a matmul operand gets commuted by
XLA's algebraic simplifier (c*(A@B)) and the matmul hoists out of the
scan — yielding impossible >100%-of-peak readings."""
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time, numpy as np, jax, jax.numpy as jnp
from jax import lax

def sync(v): return float(np.asarray(jax.device_get(v)))
PEAK = 197e12
B, H, S, D = 2, 12, 4096, 64   # bench_gpt longctx attention shape

LAT = [0.0]
def timed(f, *a, reps=1):
    sync(f(*a)); ts=[]
    for _ in range(3):
        t0=time.perf_counter(); sync(f(*a)); ts.append((time.perf_counter()-t0)/reps)
    return sorted(ts)[1] - LAT[0] / reps

def calibrate():
    tiny = jax.jit(lambda a: jnp.sum(a))
    x = jnp.ones((8, 8))
    sync(tiny(x)); ls = []
    for _ in range(5):
        t0 = time.perf_counter(); sync(tiny(x)); ls.append(time.perf_counter() - t0)
    LAT[0] = sorted(ls)[2]
    print(f"dispatch latency: {LAT[0]*1e3:.1f} ms (subtracted /reps)")
calibrate()

rng = np.random.RandomState(0)
# paddle layout (B, S, H, D) — flash_attention_fwd's contract
q = jnp.asarray(rng.randn(B, S, H, D).astype('f4')*0.1, jnp.bfloat16)
k = jnp.asarray(rng.randn(B, S, H, D).astype('f4')*0.1, jnp.bfloat16)
v = jnp.asarray(rng.randn(B, S, H, D).astype('f4')*0.1, jnp.bfloat16)
fl_attn = 2 * 2 * B * H * S * S * D * 0.5          # causal fwd flops

from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd
from paddle_tpu.nn.functional.attention import (_attention_core,
                                                _select_flash)

# --- 1. flash fwd chain (output feeds next q)
RF = 500
@jax.jit
def fwd_chain(q, k, v):
    def rep(qc, _):
        o = flash_attention_fwd(qc, k, v, causal=True)
        return (q + o * jnp.bfloat16(1e-3)).astype(jnp.bfloat16), None
    qf, _ = lax.scan(rep, q, None, length=RF)
    return jnp.sum(qf.astype(jnp.float32))
t_fwd = timed(fwd_chain, q, k, v, reps=RF)
print(f"flash fwd : {t_fwd*1e3:.3f} ms  {fl_attn/t_fwd/1e12:.1f} TF/s = {fl_attn/t_fwd/PEAK*100:.0f}% peak")

# --- 2. flash fwd+bwd chain (grad feeds next q)
RB = 200
@jax.jit
def fb_chain(q, k, v):
    def loss(qq, kk, vv):
        sel = _select_flash(qq.shape[1], kk.shape[1], qq.shape[3],
                            True, has_mask=False, mask_is_keybias=False,
                            scale=None)
        return jnp.sum(_attention_core(qq, kk, vv, True, None, sel)
                       .astype(jnp.float32))
    g = jax.grad(loss, argnums=(0,))
    def rep(qc, _):
        gq, = g(qc, k, v)
        return (q + gq.astype(jnp.bfloat16) * jnp.bfloat16(1e-3)), None
    qf, _ = lax.scan(rep, q, None, length=RB)
    return jnp.sum(qf.astype(jnp.float32))
t_fb = timed(fb_chain, q, k, v, reps=RB)
fl_fb = fl_attn * 3.5
print(f"flash f+b : {t_fb*1e3:.3f} ms  {fl_fb/t_fb/1e12:.1f} TF/s = {fl_fb/t_fb/PEAK*100:.0f}% peak")

# --- 3. dense attention fwd same shape
RD = 60
@jax.jit
def dense_chain(q, k, v):
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    def rep(qc, _):
        s = jnp.einsum("bqhd,bkhd->bhqk", qc, k) / np.sqrt(D)
        s = jnp.where(mask, s.astype(jnp.float32), -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return (q + o * jnp.bfloat16(1e-3)), None
    qf, _ = lax.scan(rep, q, None, length=RD)
    return jnp.sum(qf.astype(jnp.float32))
try:
    t_dense = timed(dense_chain, q, k, v, reps=RD)
    print(f"dense fwd : {t_dense*1e3:.3f} ms  ({t_dense/t_fwd:.2f}x flash fwd)")
except Exception as e:
    print("dense fwd : FAIL", repr(e)[:80])

# --- 4. non-attention remainder: proj+MLP block at B*S=8192 tokens
HID = 768
RM = 500
x = jnp.asarray(rng.randn(B * S, HID).astype('f4') * 0.1, jnp.bfloat16)
Wqkv = jnp.asarray(rng.randn(HID, 3 * HID).astype('f4') * 0.02, jnp.bfloat16)
Wo = jnp.asarray(rng.randn(HID, HID).astype('f4') * 0.02, jnp.bfloat16)
W1 = jnp.asarray(rng.randn(HID, 4 * HID).astype('f4') * 0.02, jnp.bfloat16)
W2 = jnp.asarray(rng.randn(4 * HID, HID).astype('f4') * 0.02, jnp.bfloat16)
@jax.jit
def mm_chain(x, Wqkv, Wo, W1, W2):
    def rep(xc, _):
        h = xc @ Wqkv
        h2 = (h[:, :HID]) @ Wo
        h3 = jax.nn.gelu(h2 @ W1)
        h4 = h3 @ W2
        return (x + h4 * jnp.bfloat16(1e-3)).astype(jnp.bfloat16), None
    xf, _ = lax.scan(rep, x, None, length=RM)
    return jnp.sum(xf.astype(jnp.float32))
t_mm = timed(mm_chain, x, Wqkv, Wo, W1, W2, reps=RM)
# NOTE: XLA DCEs the unused 2/3 of the qkv projection (only
# h[:, :HID] is consumed), so count HID not 3*HID for that matmul
fl_mm = 2 * B * S * HID * (HID + HID + 4*HID + 4*HID)
print(f"proj+mlp  : {t_mm*1e3:.3f} ms  {fl_mm/t_mm/1e12:.1f} TF/s = {fl_mm/t_mm/PEAK*100:.0f}% peak")
